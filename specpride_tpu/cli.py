"""One coherent CLI over every capability (C1-C8).

The reference spreads four inconsistent CLI styles across its scripts
(survey §5 "Config / flag system"); here a single argparse command tree:

    specpride convert    raw spectra + msms.txt + MaRaCluster TSV → clustered MGF
    specpride consensus  clustered MGF → representatives (bin-mean / gap-average)
    specpride select     clustered MGF → representatives (best-score / medoid)
    specpride evaluate   representatives + clustered MGF → quality report
    specpride plot       mirror plots (vs consensus / vs theoretical peptide)

Every compute command takes ``--backend {numpy,tpu}`` (BASELINE.json north
star) — 'numpy' is the oracle path, 'tpu' the batched device path (which
also runs on CPU when no accelerator is present).  Checkpoint/resume: with
``--checkpoint FILE`` the consensus/select commands append output per chunk
and record completed cluster ids, so an interrupted run resumes where it
stopped (survey §5 "Checkpoint / resume").
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import threading

from specpride_tpu.config import (
    BestSpectrumConfig,
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, group_into_clusters
from specpride_tpu.io.mgf import read_mgf, write_mgf
from specpride_tpu.observability import (
    MetricsRegistry,
    NullJournal,
    RunStats,
    TraceContext,
    Tracer,
    configure_logging,
    device_counters_snapshot,
    device_summary,
    device_trace,
    emit_clock_anchor,
    export_run_metrics,
    logger,
    open_journal,
)
from specpride_tpu.observability import tracing
from specpride_tpu.robustness import (
    Harness,
    OutputIntegrity,
    Quarantine,
    errors as rb_errors,
    faults as rb_faults,
)
from specpride_tpu.robustness.integrity import manifest_payload


def _add_backend(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--backend", choices=["numpy", "tpu"], default="tpu",
        help="numpy oracle or batched device execution (default tpu)",
    )
    p.add_argument(
        "--layout", choices=["auto", "flat", "bucketized"], default="auto",
        help="mesh-less device layout (escape hatch: 'bucketized' forces "
        "the (B, K) paths mesh runs use)",
    )
    p.add_argument(
        "--mesh", action="store_true",
        help="shard device batches over ALL visible devices (single-host "
        "multi-chip; implied by --coordinator)",
    )
    p.add_argument(
        "--coordinator", metavar="HOST:PORT",
        help="multi-host: jax.distributed coordinator address; every "
        "process runs the same command with its own --process-id and "
        "writes <output>.part<id> (merge with `specpride merge-parts`)",
    )
    p.add_argument("--num-processes", type=int,
                   help="multi-host: total process count")
    p.add_argument("--process-id", type=int,
                   help="multi-host: this process's rank")
    p.add_argument(
        "--force-device", action="store_true",
        help="keep the device kernels selected by --mesh/--layout even "
        "when jax exposes only CPU devices (default: gap-average routes "
        "to the vectorized host consensus there — the CPU 'device' path "
        "measured ~0.3x of it — and journals the routing decision)",
    )
    p.add_argument(
        "--compile-cache", metavar="DIR|off", default=None,
        help="persistent XLA compilation cache directory ('off' "
        "disables; default: SPECPRIDE_JAX_CACHE / JAX_COMPILATION_"
        "CACHE_DIR / a per-platform dir under ~/.cache).  An explicit "
        "DIR also caches fast compiles so a warmed rerun performs ZERO "
        "fresh XLA compiles; the resolution is journaled as a "
        "compile_cache event",
    )
    p.add_argument(
        "--routing-table", metavar="FILE",
        help="bench-derived kernel-routing override file (per-(method, "
        "platform) host-vectorized/xla/pallas decisions; default: "
        "measured static defaults, or the SPECPRIDE_ROUTING env var — "
        "see docs/performance.md)",
    )
    p.add_argument(
        "--precision", choices=["f32", "bf16", "int8"], default="f32",
        help="packed device-channel precision (default f32 = byte-parity "
        "with every earlier run).  bf16/int8 quantize the packed "
        "intensity at pack time (plus bf16 m/z where the round trip is "
        "verified exact, and exact int16 index narrowing), shrinking "
        "H2D bytes ~2-4x; non-f32 runs are validated against the f32 "
        "oracle by a QC-cosine tolerance gate (failure aborts; result "
        "journaled in run_end.precision — see docs/performance.md)",
    )
    p.add_argument(
        "--no-donate", action="store_true",
        help="disable buffer donation on the chunk loop (default: every "
        "kernel call donates its packed input buffers so XLA may alias "
        "them into outputs instead of holding both live; no-op on CPU)",
    )


def _add_execution(p: argparse.ArgumentParser) -> None:
    """Chunked-execution flags shared VERBATIM by consensus and select
    (checkpointing, the multi-lane executor, failure policy, streamed
    ingest) — one definition so the two commands can never drift."""
    p.add_argument("--append", action="store_true",
                   help="append to the output instead of replacing it")
    p.add_argument("--checkpoint", help="resume manifest path")
    p.add_argument("--checkpoint-every", type=int, default=512)
    p.add_argument(
        "--prefetch", type=int, default=2, metavar="N",
        help="pipelined chunk executor: the pack lane builds up to N "
        "chunks' device inputs ahead of dispatch (bounded queue; 0 = "
        "serial; output is byte-identical either way — see "
        "docs/performance.md)",
    )
    p.add_argument(
        "--pack-workers", type=int, default=None, metavar="N",
        help="pack lane worker pool: N threads run the host pack stage "
        "on distinct chunks concurrently, re-ordered into FIFO by a "
        "bounded reorder buffer so dispatch/checkpoint order is "
        "unchanged (default min(4, cores/4); 0 = the single dedicated "
        "packer thread; active only with --prefetch > 0)",
    )
    p.add_argument(
        "--h2d-buffer", type=int, default=0, metavar="N",
        help="double-buffered H2D: a dedicated transfer lane device_puts "
        "the NEXT chunk's packed device inputs (N slots ahead, 2 = "
        "classic double buffering) while the current chunk dispatches, "
        "so transfer hides under dispatch (pipeline:h2d spans; overlap "
        "accounted in run_end.pipeline.h2d).  Active with --prefetch > 0 "
        "on paths that stage (the flat bin-mean device path); outputs "
        "are byte-identical either way (default 0 = off)",
    )
    p.add_argument(
        "--async-write", choices=["auto", "on", "off"], default="auto",
        help="ordered write lane: QC-row finalize, MGF appends and "
        "checkpoint writes move to a dedicated committer thread with the "
        "same strict append-then-record order per chunk, so a kill at "
        "any point resumes identically (auto = on whenever the pipelined "
        "executor runs)",
    )
    p.add_argument(
        "--on-error", choices=["abort", "skip"], default="abort",
        help="chunk failure handling: abort (default) or retry the chunk "
        "cluster-by-cluster, log + record failures, and continue",
    )
    p.add_argument(
        "--stream-clusters", default="auto", metavar="N|auto|off",
        help="bounded-memory ingest: parse member spectra in windows of N "
        "clusters off a byte index instead of loading the whole MGF "
        "(default auto: streams inputs over 256 MB)",
    )
    p.add_argument(
        "--retries", type=int, default=2, metavar="N",
        help="retry transient failures (I/O errors, device resource "
        "pressure, lane hangs) up to N times per stage with exponential "
        "backoff + deterministic jitter; permanent errors (malformed "
        "input) never retry (default 2; 0 disables)",
    )
    p.add_argument(
        "--retry-backoff", type=float, default=0.05, metavar="BASE",
        help="base backoff seconds: retry i sleeps BASE * 2^i * "
        "(1 + jitter) (default 0.05)",
    )
    p.add_argument(
        "--no-degrade", action="store_true",
        help="disable graceful degradation: without it a device OOM "
        "splits the chunk in half and re-dispatches (floor 1 cluster), "
        "and repeated device failure reroutes the chunk to the numpy "
        "backend — both journaled as `degrade` events",
    )
    p.add_argument(
        "--watchdog-timeout", type=float, default=0.0, metavar="S",
        help="per-lane stall watchdog: a lane section (pack / dispatch / "
        "write) busy longer than S seconds journals a watchdog_stall "
        "event and breaks injected hangs so the retry policy recovers "
        "them (default 0 = off)",
    )
    p.add_argument(
        "--inject-faults", metavar="SPEC",
        help="deterministic fault injection for chaos testing: "
        "comma list of SITE:KIND:RATE[:AFTER[:MAX]] — sites "
        f"{{{','.join(rb_faults.FAULT_SITES)}}}, kinds "
        f"{{{','.join(rb_faults.FAULT_KINDS)}}}; every fired fault is "
        "journaled as a `fault` event (subprocess tests can use the "
        "SPECPRIDE_FAULTS env var instead; see docs/robustness.md)",
    )
    p.add_argument(
        "--fault-seed", type=int, default=0, metavar="N",
        help="seed for --inject-faults firing decisions and retry "
        "jitter: same plan + seed fires at the same visits every run",
    )
    p.add_argument(
        "--warmup", choices=["auto", "manifest", "off"], default="auto",
        help="AOT bucket-shape warmup before the pack lane starts: "
        "'auto' (default) warms from — and afterwards updates — the "
        "shape manifest beside the compile cache when one exists; "
        "'manifest' requires a manifest (--warmup-manifest or the "
        "cache-dir default) and fails loudly without one; 'off' "
        "disables.  Per-kernel compile-vs-cache-hit is journaled as "
        "warmup events (see `specpride warmup`)",
    )
    p.add_argument(
        "--warmup-manifest", metavar="FILE",
        help="shape manifest path (default: <compile-cache dir>/"
        "shape_manifest.json)",
    )
    p.add_argument(
        "--elastic", metavar="DIR|URL",
        help="elastic multi-host mode: instead of the static per-rank "
        "block partition, ranks dynamically claim chunk RANGES from a "
        "work queue in a shared directory — or, with an http(s):// "
        "URL, a conditional-put/ETag object store (no shared "
        "filesystem needed; `specpride cas-server` is the in-tree "
        "test server).  Each committed range is one <output>."
        "part<range> shard with a sha256 manifest; a rank that dies "
        "mid-range has its uncommitted chunks reassigned to a "
        "survivor, a rank that merely lags is relieved by live "
        "work-stealing (see --elastic-steal), and the merged output "
        "stays byte-identical to a single-host serial run (merge with "
        "`specpride merge-parts OUTPUT --elastic DIR|URL`).  Rank "
        "identity comes from --process-id, else auto-assigned.  See "
        "docs/robustness.md",
    )
    p.add_argument(
        "--elastic-range", type=int, default=0, metavar="N",
        help="clusters per claimable chunk range (default 0 = twice "
        "--checkpoint-every, so a reassigned range resumes from its "
        "committed chunks instead of redoing everything)",
    )
    p.add_argument(
        "--elastic-ttl", type=float, default=10.0, metavar="S",
        help="lease time-to-live: a rank that stops heartbeating for "
        "longer than S (+50%% clock-skew grace) loses its ranges to a "
        "survivor (default 10)",
    )
    p.add_argument(
        "--elastic-heartbeat", type=float, default=0.0, metavar="S",
        help="heartbeat/lease-renewal interval (default 0 = TTL/4)",
    )
    p.add_argument(
        "--elastic-steal", choices=["on", "off"], default="on",
        help="live work-stealing between LIVE ranks (default on): a "
        "rank with nothing claimable proposes a split of the "
        "most-loaded live peer's range; the donor ratifies at its next "
        "chunk boundary (journaled as lease_split) and the tail runs "
        "as a new overlay range — merged output stays byte-identical. "
        "'off' restores tier-1 behavior (only DEAD ranks lose work)",
    )
    p.add_argument(
        "--elastic-local", metavar="DIR",
        help="(object-store coordinator) local directory for the "
        "per-range resume manifests (default: <output>.elastic).  "
        "Share it between ranks on one host so takeovers resume a "
        "dead rank's committed prefix instead of recomputing",
    )
    p.add_argument(
        "--result-cache", metavar="DIR[:MB]",
        help="content-addressed consensus result cache: per-cluster "
        "results keyed by (cluster content digest, method, config "
        "digest, precision, schema rev) in a bounded local LRU tier "
        "(default cap 256 MB; DIR:MB overrides).  Hits replay the "
        "stored representative + QC cosine — output bytes and the QC "
        "report stay identical to an uncached run; corrupt entries are "
        "quarantined and recomputed (see docs/performance.md)",
    )
    p.add_argument(
        "--result-store", metavar="DIR|URL",
        help="(with --result-cache) shared second tier: a directory or "
        "http(s):// conditional-put object store (`specpride "
        "cas-server`) every rank/host populates and consults, so a "
        "fleet warms itself",
    )
    p.add_argument(
        "--autotune", choices=["off", "observe", "on"], default="off",
        help="(with --elastic) closed-loop controller re-sizing "
        "SPLIT-OFF ranges from the heartbeat EWMA chunk walls (ROADMAP "
        "4b): 'observe' journals every would-be --elastic-range "
        "decision without acting, 'on' also caps how much tail a donor "
        "cedes per steal — already-claimed ranges are never resized, "
        "merged output stays byte-identical.  Requires --journal; "
        "every decision is an `autotune` event (default off; see "
        "docs/autotune.md)",
    )


def _add_observability(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--journal", metavar="FILE",
        help="append-only JSONL run journal: typed events (run_start, "
        "chunk heartbeats, compile/dispatch, checkpoint_write, resume, "
        "run_end) an operator can tail live; multi-host runs write "
        "<FILE>.part<rank> (read with `specpride stats`)",
    )
    p.add_argument(
        "--metrics-out", metavar="FILE",
        help="write run metrics as a Prometheus textfile on exit "
        "(counters/gauges/histograms; node_exporter textfile format)",
    )
    p.add_argument(
        "--trace-dir", metavar="DIR",
        help="capture a jax.profiler device trace of the compute into "
        "this directory (view with TensorBoard / Perfetto)",
    )
    p.add_argument(
        "--chrome-trace", metavar="FILE",
        help="export the run's hierarchical span timeline (parse / pack / "
        "per-kernel dispatch / d2h / write, per chunk) as Chrome "
        "trace-event JSON, loadable in Perfetto or chrome://tracing; "
        "multi-host runs write one <FILE>.part<rank> per rank (for a "
        "single merged timeline run `specpride trace` over the "
        "--journal shards)",
    )
    p.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="(with --elastic) serve a live Prometheus /metrics "
        "endpoint: per-rank heartbeat ages "
        "(specpride_rank_heartbeat_age_seconds), ranges committed, "
        "lease-expiry/reassignment counters — a dying rank is visible "
        "on /metrics before the run fails (0 = ephemeral port; "
        "loopback unless --metrics-host widens it)",
    )
    p.add_argument(
        "--metrics-host", default="127.0.0.1", metavar="HOST",
        help="bind address for --metrics-port (default 127.0.0.1)",
    )
    p.add_argument(
        "--flightrec", choices=["off", "observe", "on"], default="off",
        help="(with --elastic) flight recorder: an always-on ring of "
        "recent journal records plus health detectors (SLO-breach "
        "streaks, latency spikes, watchdog stalls, lease churn, ...). "
        "'observe' journals each detector firing as an `incident` "
        "event; 'on' also dumps an atomic diagnostic bundle under "
        "--incident-dir; 'off' constructs no recorder at all.  "
        "Requires --journal (default off; see docs/observability.md)",
    )
    p.add_argument(
        "--incident-dir", metavar="DIR",
        help="directory for --flightrec on incident bundles (ring "
        "dump, thread stacks, /metrics snapshot, autotune knob state, "
        "config digest, journal tail; read with `specpride incidents`)",
    )


def _get_backend(args):
    if args.backend == "numpy":
        from specpride_tpu.backends import numpy_backend

        return numpy_backend
    from specpride_tpu.backends.tpu_backend import TpuBackend
    from specpride_tpu.warmstart import configure_compile_cache
    from specpride_tpu.warmstart.routing import RoutingTable

    # cache control resolves BEFORE the backend exists so the explicit
    # --compile-cache flag beats the constructor's default resolution
    configure_compile_cache(getattr(args, "compile_cache", None))
    routing = RoutingTable.load(getattr(args, "routing_table", None))

    mesh = None
    if getattr(args, "coordinator", None) or getattr(args, "mesh", False):
        from specpride_tpu.parallel.mesh import (
            cluster_mesh,
            initialize_distributed,
        )

        import jax

        initialize_distributed(
            getattr(args, "coordinator", None),
            getattr(args, "num_processes", None),
            getattr(args, "process_id", None),
        )
        # clusters are independent, so scale-out is pure data parallelism:
        # each process owns a block of clusters and runs them on its OWN
        # devices.  A global mesh would force every process to device_put
        # identical global arrays (jax asserts it) — exactly wrong for
        # sharded inputs, and it buys nothing when no collective ever
        # crosses hosts.
        # a silently failed bring-up (e.g. a PJRT plugin overriding the
        # platform) leaves every process believing it is rank 0 of 1 —
        # all would then compute the FULL input and overwrite the same
        # part file, so fail loudly instead
        want = getattr(args, "num_processes", None)
        if (
            getattr(args, "coordinator", None)
            and want
            and jax.process_count() != want
        ):
            raise SystemExit(
                f"distributed bring-up failed: jax reports "
                f"{jax.process_count()} process(es), --num-processes said "
                f"{want} (is another PJRT plugin overriding the platform?)"
            )
        local = (
            jax.local_devices() if jax.process_count() > 1 else None
        )
        mesh = cluster_mesh(local)
        logger.info(
            "device mesh: %d local devices, %d processes",
            mesh.size, jax.process_count(),
        )
    return TpuBackend(
        mesh=mesh, layout=getattr(args, "layout", "auto"),
        force_device=getattr(args, "force_device", False),
        routing=routing,
        precision=getattr(args, "precision", "f32") or "f32",
        donate=not getattr(args, "no_donate", False),
    )


def _shard_for_process(clusters: list, args) -> tuple[list, str]:
    """Multi-host input sharding: each process takes a contiguous BLOCK of
    clusters (block order keeps `merge-parts` output identical to a
    single-host run) and writes ``<output>.part<id>``.  Single-process runs
    pass through untouched (BASELINE config 5; survey §2 parallelism).

    Also renames any ``--checkpoint`` to a per-rank manifest — the rank
    comes from ``jax.process_index()`` (NOT ``--process-id``, which may be
    absent when jax auto-detects ranks), so manifests never collide on a
    shared filesystem."""
    if getattr(args, "elastic", None):
        # elastic mode shards DYNAMICALLY: ranges are claimed from the
        # coordinator queue, outputs are per-range, and the per-rank
        # telemetry renames happen in _run_elastic once the rank id is
        # known (it may be auto-assigned, not --process-id)
        return clusters, args.output
    if not getattr(args, "coordinator", None):
        return clusters, args.output
    import jax

    pid, nproc = jax.process_index(), jax.process_count()
    chunk = -(-len(clusters) // max(nproc, 1))
    mine = clusters[pid * chunk : (pid + 1) * chunk]
    part = f"{args.output}.part{pid:05d}"
    if getattr(args, "checkpoint", None):
        args.checkpoint = f"{args.checkpoint}.part{pid:05d}"
    if getattr(args, "qc_report", None):
        # per-rank QC shards too — every rank writing the same JSON path
        # would leave a last-writer-wins report covering one shard
        args.qc_report = f"{args.qc_report}.part{pid:05d}"
    # per-rank telemetry: concurrent appends to one journal would interleave
    # events across ranks (and one metrics file would be last-writer-wins);
    # `specpride stats` re-merges the parts rank-aware like `merge-parts`
    if getattr(args, "journal", None):
        args.journal = f"{args.journal}.part{pid:05d}"
    if getattr(args, "metrics_out", None):
        args.metrics_out = f"{args.metrics_out}.part{pid:05d}"
    if getattr(args, "chrome_trace", None):
        args.chrome_trace = f"{args.chrome_trace}.part{pid:05d}"
    quarantine = getattr(args, "_quarantine", None)
    if quarantine is not None:
        # every rank parses the FULL input (load precedes sharding), so
        # without a per-rank file N ranks would append the same blocks
        # to one shared quarantine concurrently
        quarantine.rename(f"{quarantine.path}.part{pid:05d}")
    logger.info(
        "process %d/%d: %d of %d clusters -> %s",
        pid, nproc, len(mine), len(clusters), part,
    )
    return mine, part


def _load_scores(args) -> dict[str, float]:
    """Resolve the --method best score source ONCE per command (a real
    msms.txt is hundreds of MB — it must not be re-parsed per chunk)."""
    from specpride_tpu.io.maxquant import (
        read_msms_scores,
        read_percolator_scores,
    )

    if getattr(args, "psms", None):
        return read_percolator_scores(
            args.psms, args.px_accession,
            raw_name=getattr(args, "raw_name", None),
        )
    if args.msms:
        return read_msms_scores(args.msms, args.px_accession)
    raise SystemExit(
        "select --method best needs a score source: --msms "
        "(MaxQuant msms.txt) or --psms (percolator/crux TSV)"
    )


def _cosine_config(args) -> CosineConfig:
    return CosineConfig(
        normalization=getattr(args, "qc_normalization", None) or "none"
    )


def _cosines_of(backend, reps, clusters, config=None):
    """Mean member cosine per cluster on whichever backend is active."""
    config = config or CosineConfig()
    if hasattr(backend, "average_cosines"):  # device backend: one dispatch
        return backend.average_cosines(reps, clusters, config)
    return [
        backend.average_cosine(r, c.members, config)
        for r, c in zip(reps, clusters)
    ]


def _append_qc_rows(qc: list, clusters, cosines) -> None:
    qc.extend(
        {"cluster_id": c.cluster_id, "n_members": c.n_members,
         "avg_cosine": float(v)}
        for c, v in zip(clusters, cosines)
    )


def _write_qc_report(
    args, backend, clusters, qc: list, stats, resumed_ids: set[str],
    failed_ids: list[str] = (), qc_failed_ids: list[str] = (),
) -> None:
    """Finalize and write the per-cluster QC report.

    A resume skips clusters already in the manifest, so their cosines were
    never computed this run — recompute them from the representatives
    already in the output, so the report always covers the full input.
    Only resume-skipped ids are candidates: clusters a method deliberately
    dropped (scoreless best-spectrum, --on-error skip) must not trigger a
    futile re-parse of the whole output."""
    have = {row["cluster_id"] for row in qc}
    all_ids = (
        clusters.cluster_ids
        if hasattr(clusters, "cluster_ids")
        else [c.cluster_id for c in clusters]
    )
    missing_idx = [
        i for i, cid in enumerate(all_ids)
        if cid in resumed_ids and cid not in have
    ]
    if missing_idx:
        reps_by_id = {s.cluster_id: s for s in read_mgf(args.output)}
        # windowed so a streamed input stays memory-bounded during the
        # resume recompute (clusters[i] materialises one window at a time)
        w = getattr(clusters, "window", 0) or len(missing_idx)
        for b0 in range(0, len(missing_idx), w):
            batch = [clusters[i] for i in missing_idx[b0 : b0 + w]]
            pairs = [
                (reps_by_id[c.cluster_id], c)
                for c in batch
                if c.cluster_id in reps_by_id and c.n_members > 0
            ]
            if pairs:
                with stats.phase("compute"):
                    _append_qc_rows(
                        qc,
                        [c for _, c in pairs],
                        _cosines_of(
                            backend, [r for r, _ in pairs],
                            [c for _, c in pairs], _cosine_config(args),
                        ),
                    )
    order = {cid: i for i, cid in enumerate(all_ids)}
    qc.sort(key=lambda row: order.get(row["cluster_id"], len(order)))
    cosines = [row["avg_cosine"] for row in qc]
    import statistics

    # rows can be missing for two distinct reasons consumers must be able
    # to tell apart: the METHOD dropped/failed the cluster (failed_ids,
    # scoreless best-spectrum) vs the QC cosine pass itself failed
    # (qc_failed_ids) — n_clusters shrinking alone is ambiguous
    have = {row["cluster_id"] for row in qc}
    qc_failed = sorted(i for i in qc_failed_ids if i not in have)
    report = {
        "summary": {
            "n_clusters": len(qc),
            "mean_cosine": statistics.fmean(cosines) if cosines else None,
            "median_cosine": statistics.median(cosines) if cosines else None,
            "n_input_clusters": len(clusters),
            "n_method_failed": len(failed_ids),
            "n_qc_failed": len(qc_failed),
            **(
                {"method_failed_cluster_ids": sorted(failed_ids)}
                if failed_ids else {}
            ),
            **(
                {"qc_failed_cluster_ids": qc_failed} if qc_failed else {}
            ),
        },
        "clusters": qc,
    }
    with open(args.qc_report, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    logger.info("QC report -> %s", args.qc_report)


def _bin_mean_config(args) -> BinMeanConfig:
    """Build (and thereby validate) the bin-mean config.  Called once up
    front by cmd_consensus so bad grid options fail fast as a usage error
    — inside the chunked runner a ValueError would be misattributed to
    the chunk's clusters under --on-error skip."""
    return BinMeanConfig(
        min_mz=args.min_mz, max_mz=args.max_mz, bin_size=args.bin_size,
        apply_peak_quorum=not args.no_quorum,
        quorum_fraction=args.quorum_fraction,
        tolerance_mode=getattr(args, "tolerance_mode", "da"),
        ppm=getattr(args, "ppm", 20.0),
    )


def _method_config(method: str, args):
    """The method's config object, built once per chunk — shared by the
    serial ``_run_method`` and the pipelined executor's packer thread
    (``TpuBackend.prepare_chunk`` takes the same object)."""
    if method == "bin-mean":
        return _bin_mean_config(args)
    if method == "gap-average":
        return GapAverageConfig(
            mz_accuracy=args.mz_accuracy, dyn_range=args.dyn_range,
            min_fraction=args.min_fraction, tail_mode=args.tail_mode,
            pepmass=args.pepmass, rt=args.rt,
        )
    if method == "medoid":
        return MedoidConfig(bin_size=args.xcorr_bin)
    if method == "best":
        return BestSpectrumConfig(px_accession=args.px_accession)
    raise ValueError(method)


def _run_method(backend, method: str, clusters, args, scores=None,
                qc: list | None = None):
    config = _method_config(method, args)
    if method == "bin-mean":
        if qc is not None and hasattr(backend, "run_bin_mean_with_cosines"):
            # fused consensus + QC: the cosine member prep overlaps the
            # consensus D2H stream (see TpuBackend.run_bin_mean_with_cosines)
            reps, cosines = backend.run_bin_mean_with_cosines(
                clusters, config, _cosine_config(args)
            )
            _append_qc_rows(qc, clusters, cosines)
            return reps
        return backend.run_bin_mean(clusters, config)
    if method == "gap-average":
        return backend.run_gap_average(clusters, config)
    if method == "medoid":
        return backend.run_medoid(clusters, config)
    if method == "best":
        if scores is None:
            scores = _load_scores(args)
        return backend.run_best_spectrum(clusters, scores, config)
    raise ValueError(method)


class _ChunkItem:
    """One unit of work flowing from the packer lane to the dispatch lane
    of the pipelined chunk executor (or yielded inline when serial)."""

    __slots__ = (
        "index", "idxs", "part", "prepared", "pack_stats", "error",
        "wait_s", "cached",
    )

    def __init__(self, index: int, idxs: list[int]):
        self.index = index
        self.idxs = idxs
        self.part = None  # materialized clusters (None if packing died)
        self.prepared = None  # backend PreparedChunk (None = no split)
        self.pack_stats = None  # packer-thread RunStats to merge at handoff
        self.error = None  # exception raised while packing
        self.wait_s = 0.0  # consumer starvation waiting for this item
        self.cached = None  # result-cache consult map (None = not consulted)


def _serial_chunks(clusters, worklist):
    """--prefetch 0: materialize each chunk inline, exactly the pre-
    pipeline execution order."""
    for chunk_index, idxs in worklist:
        item = _ChunkItem(chunk_index, idxs)
        item.part = [clusters[i] for i in idxs]
        yield item


def _pack_chunk(
    clusters, chunk_index: int, idxs: list, prepare, method: str, config,
    cos_config, span_name: str, harness: Harness | None = None,
    rc=None, **span_labels,
):
    """THE per-chunk pack stage — the one copy the dedicated packer and
    every pool worker run, so the ``--pack-workers 0`` and ``>= 1`` paths
    can never drift behaviorally: materialize the chunk's clusters, run
    the backend's host pack (``prepare_chunk``) into a PRIVATE RunStats,
    and capture any exception on the item for the consumer's --on-error
    policy.  Returns ``(item, busy_seconds)``.

    Robustness: the whole stage runs inside the harness's pack-lane
    retry wrapper — the ``parse`` fault site fires in chunk
    materialization (the MGF window parse on streamed inputs), ``pack``
    before the backend pack, ``prepare`` inside ``prepare_chunk`` — so
    a transient failure anywhere in the stage re-runs it (both halves
    are pure functions of the chunk) instead of poisoning the item.
    Only errors that survive the retry budget reach the consumer."""
    import time as _time

    item = _ChunkItem(chunk_index, idxs)
    pack_stats = RunStats()
    t0 = _time.perf_counter()

    def _stage():
        # the watchdog section covers ONE attempt's real work — it must
        # sit inside the retried fn, not around retry_call, or the
        # backoff sleeps between attempts would read as a lane stall
        section = (
            harness.section("pack") if harness is not None
            else contextlib.nullcontext()
        )
        with section, tracing.span(
            span_name, chunk_index=chunk_index, n_clusters=len(idxs),
            **span_labels,
        ):
            with pack_stats.phase("pack"):
                rb_faults.check("parse")
                item.part = [clusters[i] for i in idxs]
            rb_faults.check("pack")
            if rc is not None and item.cached is None:
                # result-cache consult rides the pack lane so digesting
                # overlaps dispatch; retries keep the first verdict
                item.cached = rc.consult(item.part)
            to_pack = item.part
            if item.cached:
                hit = rc.hit_ids(item.cached)
                to_pack = [
                    c for c in item.part if c.cluster_id not in hit
                ]
            if prepare is not None and to_pack:
                item.prepared = prepare(
                    method, to_pack, config,
                    cos_config=cos_config, stats=pack_stats,
                )

    try:
        if harness is not None:
            harness.retry_call("pack", _stage)
        else:
            _stage()
    except Exception as e:  # noqa: BLE001 - handed to consumer
        item.error = e
    item.pack_stats = pack_stats
    return item, _time.perf_counter() - t0


def _capture_lane_context() -> tuple:
    """Snapshot the RUN-scoped thread context (tracer + plan-cache
    scope) on the thread that is about to spawn lane threads.  One-shot
    runs install both process-globally, so the capture is a no-op pair;
    on a serving worker lane both are thread-scoped and the lane threads
    must adopt them explicitly or the run's spans and plan-cache traffic
    fall out of its journal attribution."""
    from specpride_tpu.data.packed import current_plan_scope

    return tracing.current(), current_plan_scope()


def _adopt_lane_context(ctx: tuple) -> None:
    """First statement of every lane thread: install the creating
    thread's run context (see ``_capture_lane_context``).  The thread is
    per-run and dies with it, so nothing needs restoring."""
    from specpride_tpu.data.packed import set_plan_scope

    tracer, plan_scope = ctx
    tracing.set_thread_current(tracer)
    set_plan_scope(plan_scope)


def _default_pack_workers() -> int:
    """Default ``--pack-workers``: min(4, cores/4), floored at 1.  A
    quarter of the host saturates the dispatch lane on every profile
    measured so far (pack is at most a few times compute+write per
    chunk) without starving the dispatch/QC/write lanes of cores."""
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    return max(1, min(4, cores // 4))


def _pipelined_chunks(
    clusters, worklist, backend, method, args, prefetch: int, want_qc: bool,
    lanes: dict, harness: Harness | None = None,
):
    """Producer–consumer pipeline over the chunk worklist.

    A single background packer thread runs ahead of the dispatch lane:
    it materializes each chunk's clusters (for streamed inputs this is
    the MGF window parse) and runs the backend's host pack stage
    (``prepare_chunk``), pushing finished chunks through a bounded queue
    of depth ``prefetch``.  The consumer (this generator, resumed on the
    caller's thread) pops in FIFO order, so chunk writes stay in input
    order by construction and the crash-safety contract of
    ``_checkpointed_run`` is untouched.

    Threading contract: the packer touches only host numpy (tables, flat
    packs, cosine member prep) plus a PRIVATE per-chunk RunStats; all
    device dispatch, QC, writes and checkpointing stay on the consumer
    thread.  Pack failures are delivered as ``item.error`` so
    ``--on-error skip`` keeps its per-cluster serial-retry isolation; an
    aborting consumer sets ``stop`` and drains the queue so the packer
    can never deadlock on a full queue.

    Telemetry: each pack runs under a ``pipeline:pack`` span (packer
    lane); consumer starvation >= 1 ms is recorded as a
    ``pipeline:idle`` span and summed into the run's ``device_idle_s``;
    the packer's busy seconds accumulate into ``lanes["pack_busy_s"]``
    for the run_end per-lane summary."""
    import queue
    import threading
    import time as _time

    q: queue.Queue = queue.Queue(maxsize=max(prefetch, 1))
    stop = threading.Event()
    # lane-thread context: the packer inherits the RUN's tracer and
    # plan-cache scope from this (dispatch) thread — on a serving worker
    # lane both are thread-scoped, so without the hand-off the packer's
    # spans and plan traffic would fall out of the job's attribution
    run_ctx = _capture_lane_context()
    config = _method_config(method, args)
    cos_config = (
        _cosine_config(args) if want_qc and method == "bin-mean" else None
    )
    prepare = getattr(backend, "prepare_chunk", None)
    rc = getattr(args, "_result_cache", None)
    busy = [0.0]
    lanes["pack_busy_s"] = busy

    def _put(obj) -> bool:
        # bounded wait on the shared abort event, not a bare
        # except/continue loop: when the dispatch lane aborts with the
        # queue full, the packer parks on `stop` (0 CPU) and exits
        # within one wait quantum instead of hammering put() — the
        # consumer's finally drains the queue, so a live consumer always
        # opens a slot within the put timeout
        while True:
            if stop.is_set():
                return False
            try:
                q.put(obj, timeout=0.1)
                return True
            except queue.Full:
                if stop.wait(timeout=0.05):
                    return False

    def _packer() -> None:
        _adopt_lane_context(run_ctx)
        try:
            for chunk_index, idxs in worklist:
                if stop.is_set():
                    return
                item, elapsed = _pack_chunk(
                    clusters, chunk_index, idxs, prepare, method, config,
                    cos_config, "pipeline:pack", harness=harness, rc=rc,
                )
                busy[0] += elapsed
                if not _put(item):
                    return
        finally:
            _put(None)

    t = threading.Thread(
        target=_packer, name="specpride-packer", daemon=True
    )
    t.start()
    try:
        while True:
            t0 = _time.perf_counter()
            item = q.get()
            waited = _time.perf_counter() - t0
            if item is None:
                break
            item.wait_s = waited
            if waited >= 1e-3:
                # the dispatch lane sat starved waiting for the packer —
                # visible as its own gap span on the trace timeline
                tracing.current().complete(
                    "pipeline:idle", t0, waited, chunk_index=item.index
                )
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join()


def _pooled_chunks(
    clusters, worklist, backend, method, args, prefetch: int, want_qc: bool,
    n_workers: int, lanes: dict, harness: Harness | None = None,
):
    """Pack worker pool (``--pack-workers N``): N threads run the host
    pack stage (chunk materialization + ``prepare_chunk``) on DISTINCT
    chunks concurrently, and a bounded reorder buffer releases finished
    chunks to the dispatch lane strictly in worklist order — so dispatch
    order, and therefore checkpoint/resume and ``--on-error skip``
    semantics, are identical to the single-packer and serial paths.

    Threading contract: identical to ``_pipelined_chunks`` per worker —
    pure host numpy plus a PRIVATE per-chunk RunStats; chunks never
    share mutable state (the backend's prepare path touches no backend
    state, the plan cache and native-library loaders are
    lock-protected, and a streamed input's window cache is widened to
    ``n_workers + 1`` slots below so workers on distinct windows don't
    evict each other).  At most ``max(prefetch, n_workers)`` chunks are
    outstanding (packing or buffered) at once, so memory stays bounded.

    Telemetry: worker *i* packs under ``pipeline:pack[i]`` spans (one
    Chrome track per worker via the span ``tid`` lane) and accumulates
    its busy seconds into ``lanes["pack_busy_s"][i]``; head-of-line
    blocking — the consumer starved for chunk *s* while LATER chunks sat
    finished in the reorder buffer — accumulates into
    ``lanes["reorder_stall_s"]``."""
    import threading
    import time as _time

    config = _method_config(method, args)
    cos_config = (
        _cosine_config(args) if want_qc and method == "bin-mean" else None
    )
    prepare = getattr(backend, "prepare_chunk", None)
    rc = getattr(args, "_result_cache", None)
    n_workers = max(1, min(n_workers, len(worklist)))
    depth = max(prefetch, n_workers)
    run_ctx = _capture_lane_context()  # see _pipelined_chunks
    admit = threading.Semaphore(depth)
    stop = threading.Event()
    cond = threading.Condition()
    buf: dict[int, _ChunkItem] = {}
    state = {"next_task": 0, "exited": 0}
    busy = [0.0] * n_workers
    lanes["pack_busy_s"] = busy
    if hasattr(clusters, "cache_slots"):
        # streamed input: one window slot per worker plus the consumer's
        # serial-retry re-walk, so concurrent lookahead can't thrash
        clusters.cache_slots = max(
            int(getattr(clusters, "cache_slots", 2)), n_workers + 1
        )

    def _worker(wid: int) -> None:
        _adopt_lane_context(run_ctx)
        claimed: int | None = None  # claimed but not yet delivered
        try:
            while True:
                admit.acquire()
                if stop.is_set():
                    return
                with cond:
                    seq = state["next_task"]
                    if seq >= len(worklist):
                        return
                    state["next_task"] = seq + 1
                claimed = seq
                chunk_index, idxs = worklist[seq]
                item, elapsed = _pack_chunk(
                    clusters, chunk_index, idxs, prepare, method, config,
                    cos_config, f"pipeline:pack[{wid}]", harness=harness,
                    rc=rc, worker=wid,
                )
                busy[wid] += elapsed
                with cond:
                    buf[seq] = item
                    claimed = None
                    cond.notify_all()
        finally:
            with cond:
                if claimed is not None:
                    # the worker is dying BETWEEN claim and delivery
                    # (BaseException outside _pack_chunk's guard, e.g.
                    # MemoryError): deliver the claim as an errored item
                    # so the consumer applies its --on-error policy
                    # instead of waiting on a chunk nobody owns
                    chunk_index, idxs = worklist[claimed]
                    it = _ChunkItem(chunk_index, idxs)
                    it.error = RuntimeError(
                        f"pack worker {wid} died packing chunk {chunk_index}"
                    )
                    buf.setdefault(claimed, it)
                state["exited"] += 1
                cond.notify_all()

    threads = [
        threading.Thread(
            target=_worker, args=(w,), name=f"specpride-packer-{w}",
            daemon=True,
        )
        for w in range(n_workers)
    ]
    for t in threads:
        t.start()
    stall = 0.0
    try:
        for seq in range(len(worklist)):
            t_wait = _time.perf_counter()
            with cond:
                while seq not in buf:
                    if state["exited"] == n_workers:
                        # a worker died between claiming and delivering a
                        # chunk (BaseException escaped the handler)
                        raise RuntimeError(
                            "pack worker pool exited without delivering "
                            f"chunk {seq}"
                        )
                    blocked = bool(buf)
                    seg0 = _time.perf_counter()
                    cond.wait(0.1)
                    if blocked:
                        stall += _time.perf_counter() - seg0
                item = buf.pop(seq)
            waited = _time.perf_counter() - t_wait
            item.wait_s = waited
            if waited >= 1e-3:
                tracing.current().complete(
                    "pipeline:idle", t_wait, waited, chunk_index=item.index
                )
            admit.release()
            yield item
    finally:
        stop.set()
        for _ in threads:
            admit.release()  # unblock workers parked on the admit gate
        with cond:
            cond.notify_all()
        for t in threads:
            t.join()
        lanes["reorder_stall_s"] = lanes.get("reorder_stall_s", 0.0) + stall


def _h2d_staged_chunks(
    items, backend, slots: int, lanes: dict,
):
    """Double-buffered H2D transfer lane (``--h2d-buffer N``).

    Sits between the pack lane and the dispatch lane: a dedicated
    transfer thread pulls packed chunks in FIFO order, pre-transfers
    each stageable chunk's device arguments (``backend.stage_chunk`` —
    one batched ``device_put`` per flat chunk, under a ``pipeline:h2d``
    span) into a bounded queue of ``slots`` entries, so while chunk i
    dispatches, chunk i+1's H2D is already on the wire.  Two slots is
    classic double buffering; the bound caps device memory at
    ``slots`` staged chunks.

    Order and error semantics are untouched: items flow FIFO, a staging
    failure lands on ``item.error`` for the consumer's --on-error
    policy (the staged buffers are consumed exactly once — a dispatch
    retry re-puts from host numpy, so buffer donation never sees a
    stale staged array).  Lane telemetry: busy seconds, staged bytes,
    and the lane's wait on the pack lane accumulate in ``lanes`` for
    the run_end ``pipeline.h2d`` summary."""
    import queue
    import threading
    import time as _time

    q: queue.Queue = queue.Queue(maxsize=max(slots, 1))
    stop = threading.Event()
    run_ctx = _capture_lane_context()
    busy = [0.0]
    staged_bytes = [0]
    upstream_wait = [0.0]
    lanes["h2d_busy_s"] = busy
    lanes["h2d_bytes"] = staged_bytes
    lanes["h2d_upstream_wait_s"] = upstream_wait

    def _put(obj) -> bool:
        # same bounded-wait-on-abort protocol as the pack lane
        while True:
            if stop.is_set():
                return False
            try:
                q.put(obj, timeout=0.1)
                return True
            except queue.Full:
                if stop.wait(timeout=0.05):
                    return False

    upstream_error: list = [None]

    def _stager() -> None:
        _adopt_lane_context(run_ctx)
        it = iter(items)
        try:
            while True:
                t_wait = _time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                except BaseException as e:  # noqa: BLE001 - re-raised
                    # an upstream pack-lane failure (e.g. the pool
                    # exiting without delivering a chunk) must ABORT
                    # the run on the dispatch lane, exactly as it does
                    # without the h2d lane — swallowing it here would
                    # end the stream early and commit a silently
                    # truncated output
                    upstream_error[0] = e
                    return
                upstream_wait[0] += _time.perf_counter() - t_wait
                if stop.is_set():
                    return
                if (
                    item.error is None
                    and item.prepared is not None
                    and getattr(backend, "supports_h2d_stage", None)
                    and backend.supports_h2d_stage(item.prepared)
                ):
                    t0 = _time.perf_counter()
                    try:
                        with tracing.span(
                            "pipeline:h2d", chunk_index=item.index,
                        ):
                            staged_bytes[0] += backend.stage_chunk(
                                item.prepared
                            )
                    except Exception as e:  # noqa: BLE001 - to consumer
                        item.error = e
                    busy[0] += _time.perf_counter() - t0
                if not _put(item):
                    return
        finally:
            _put(None)

    t = threading.Thread(
        target=_stager, name="specpride-h2d", daemon=True
    )
    t.start()
    try:
        while True:
            t0 = _time.perf_counter()
            item = q.get()
            waited = _time.perf_counter() - t0
            if item is None:
                if upstream_error[0] is not None:
                    raise upstream_error[0]
                break
            item.wait_s = waited
            if waited >= 1e-3:
                tracing.current().complete(
                    "pipeline:idle", t0, waited, chunk_index=item.index
                )
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join()
        # the upstream pack generator was being driven by the stager
        # thread; it is parked now the thread is joined, so closing it
        # here shuts the pack lanes promptly (not at GC time)
        close = getattr(items, "close", None)
        if close is not None:
            close()


class _CommitItem:
    """One finished chunk handed from the dispatch lane to the ordered
    write lane: everything the commit protocol needs, snapshotted on the
    dispatch lane so commits are byte-identical to serial runs."""

    __slots__ = ("index", "reps", "part_ids", "qc_rows", "failed",
                 "chunk_t0", "max_idx", "populate")

    def __init__(self, index, reps, part_ids, qc_rows, failed, chunk_t0,
                 max_idx=None):
        self.index = index
        self.reps = reps
        self.part_ids = part_ids
        self.qc_rows = qc_rows  # finalized QC rows for this chunk (or None)
        self.failed = failed  # sorted failure snapshot at submit time
        self.chunk_t0 = chunk_t0
        # highest LOCAL cluster index in this chunk — what the elastic
        # commit fence compares against a ratified split cut
        self.max_idx = max_idx
        # result-cache entries to commit AFTER the append lands:
        # (key, rep, cluster, cosine) per freshly computed cluster
        self.populate = None


def _commit_chunk(item: _CommitItem, args, journal, stats: RunStats,
                  qc: list, done: set, first_write: bool,
                  integrity: OutputIntegrity | None = None,
                  harness: Harness | None = None) -> None:
    """THE chunk commit protocol — the one copy both the inline (sync)
    tail of ``_checkpointed_run`` and the ``_Committer`` lane execute, so
    ``--async-write on`` and ``off`` can never drift: QC-row finalize,
    MGF append, counters, the ``chunk_done`` heartbeat, then (with a
    checkpoint) the atomic schema-v2 ``{done, output_bytes, sha256,
    failed}`` manifest replace — strictly AFTER the append, so a kill
    between the two leaves output past the manifest, the state resume
    truncates.

    Robustness: both steps run under the harness's retry policy.  The
    append's retry hook first truncates any partial append back to the
    pre-commit offset, so a transient write failure can never duplicate
    records; the manifest replace is atomic already, so its retry needs
    no undo.  ``integrity`` maintains the running sha256 of the
    committed prefix that lands in the manifest."""
    import time as _time

    fence = getattr(args, "_elastic_fence", None)
    if fence is not None:
        # elastic mode: prove this rank STILL holds the range's lease —
        # and, tier 2, that this chunk sits below any ratified split
        # cut — before any bytes land.  A rank that stalled past its
        # TTL (or a zombie donor dispatching past its cut) gets
        # LeaseExpiredError (permanent — no retry) and abandons instead
        # of racing the rank that took the work over.  The fence also
        # folds this chunk's wall into the progress mirror peers use to
        # pick steal targets.
        fence(item)
    if item.qc_rows:
        qc.extend(item.qc_rows)
    pre_bytes = (
        os.path.getsize(args.output)
        if not first_write and os.path.exists(args.output) else 0
    )

    def _section():
        # per-attempt watchdog coverage: inside the retried fn so the
        # backoff sleeps between attempts never read as a lane stall
        return (
            harness.section("write") if harness is not None
            else contextlib.nullcontext()
        )

    def _append() -> None:
        with _section():
            rb_faults.check("write")
            with stats.phase("write"):
                write_mgf(item.reps, args.output, append=not first_write)

    def _undo_partial_append() -> None:
        # a failed append may still have landed bytes; drop back to the
        # pre-commit offset so the retry appends exactly once (first
        # writes re-open with mode "w" — truncation built in)
        if not first_write and os.path.exists(args.output) and (
            os.path.getsize(args.output) > pre_bytes
        ):
            with open(args.output, "r+b") as fh:
                fh.truncate(pre_bytes)

    if harness is not None:
        harness.retry_call(
            "write", _append, before_retry=_undo_partial_append
        )
    else:
        _append()
    output_bytes = os.path.getsize(args.output)
    if integrity is not None:
        if first_write:
            integrity.reset()
        integrity.absorb(args.output, output_bytes)
    stats.count("clusters", len(item.part_ids))
    stats.count("representatives", len(item.reps))
    done.update(item.part_ids)
    dt = _time.perf_counter() - item.chunk_t0
    journal.emit(
        "chunk_done", chunk_index=item.index,
        n_clusters=len(item.part_ids),
        n_representatives=len(item.reps), elapsed_s=round(dt, 4),
        clusters_per_sec=round(len(item.part_ids) / dt, 2)
        if dt > 0 else 0.0,
    )
    if args.checkpoint:
        def _replace_manifest() -> None:
            with _section():
                rb_faults.check("checkpoint_write")
                _replace_manifest_inner()

        def _replace_manifest_inner() -> None:
            with tracing.span("checkpoint_write", n_done=len(done)):
                tmp = args.checkpoint + ".tmp"
                payload = (
                    manifest_payload(
                        done, output_bytes, integrity, failed=item.failed
                    )
                    if integrity is not None
                    else {
                        "done": sorted(done),
                        "output_bytes": output_bytes,
                        **({"failed": item.failed} if item.failed else {}),
                    }
                )
                with open(tmp, "w") as fh:
                    json.dump(payload, fh)
                os.replace(tmp, args.checkpoint)

        if harness is not None:
            harness.retry_call("checkpoint_write", _replace_manifest)
        else:
            _replace_manifest()
        journal.emit(
            "checkpoint_write", n_done=len(done),
            output_bytes=output_bytes,
        )
    rc = getattr(args, "_result_cache", None)
    if rc is not None and item.populate:
        # populate strictly AFTER the bytes landed (and the manifest,
        # when checkpointing): a crash mid-chunk must never leave cache
        # entries for output that was truncated away on resume.  The
        # populate itself is best-effort — failures are contained.
        rc.populate(item.populate)


class _Committer:
    """Ordered async write/checkpoint lane (``--async-write``).

    A dedicated committer thread consumes finished chunks FIFO from a
    bounded queue and runs, per chunk, exactly the serial tail of
    ``_checkpointed_run``: QC-row finalize, MGF append, then the atomic
    ``{done, output_bytes, failed}`` manifest replace.  The checkpoint
    for chunk *i* is written only after chunk *i*'s MGF bytes are
    flushed (the writer closes the file before ``getsize``), so a kill
    at ANY point leaves the same on-disk states a serial run can leave
    and resume behaves identically.

    The lane owns ``done``/``first_write``/the shared QC list from
    construction on — the dispatch lane must not touch them again.
    Phase time and counters accumulate in a private ``RunStats`` merged
    into the run's stats at ``finish``/``shutdown`` (``RunStats.merge``
    is not thread-safe, so the fold happens after the join).  A commit
    error is re-raised on the dispatch lane at the next ``submit`` or
    at ``finish``; after an error the lane keeps draining its queue so
    the dispatch lane can never deadlock on a full queue."""

    def __init__(self, args, journal, qc, done: set, first_write: bool,
                 depth: int, integrity: OutputIntegrity | None = None,
                 harness: Harness | None = None):
        import queue
        import threading

        self._args = args
        self._journal = journal
        self._qc = qc
        self._done = done
        self._first_write = first_write
        self._integrity = integrity
        self._harness = harness
        self.stats = RunStats()
        self.busy_s = 0.0
        self.error: BaseException | None = None
        self._merged = False
        self._run_ctx = _capture_lane_context()  # see _pipelined_chunks
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._thread = threading.Thread(
            target=self._run, name="specpride-committer", daemon=True
        )
        self._thread.start()

    def submit(self, item: _CommitItem) -> None:
        if self.error is not None:
            self.finish(None)  # raises the commit error on this lane
        self._q.put(item)

    def _run(self) -> None:
        import time as _time

        _adopt_lane_context(self._run_ctx)
        while True:
            item = self._q.get()
            if item is None:
                return
            if self.error is not None:
                continue  # drain without acting; submit() will re-raise
            t0 = _time.perf_counter()
            try:
                with tracing.span(
                    "pipeline:write", chunk_index=item.index,
                    n_clusters=len(item.part_ids),
                ):
                    self._commit(item)
            except BaseException as e:  # noqa: BLE001 - re-raised on submit
                self.error = e
            self.busy_s += _time.perf_counter() - t0

    def _commit(self, item: _CommitItem) -> None:
        # watchdog sections open inside _commit_chunk's retried steps,
        # so retry backoff on this lane never reads as a stall
        _commit_chunk(
            item, self._args, self._journal, self.stats, self._qc,
            self._done, self._first_write, integrity=self._integrity,
            harness=self._harness,
        )
        self._first_write = False

    def finish(self, stats: RunStats | None) -> None:
        """Flush every queued commit, stop the lane, fold its counters
        and phase time into ``stats``, and re-raise any commit error."""
        self.shutdown(stats)
        if self.error is not None:
            err, self.error = self.error, None
            raise err

    def shutdown(self, stats: RunStats | None) -> None:
        """Idempotent stop: drain + join, merge once, never raise."""
        if self._thread.is_alive():
            self._q.put(None)
        self._thread.join()
        if stats is not None and not self._merged:
            self._merged = True
            stats.merge(self.stats)


def _dispatch_chunk(
    backend, method, item: _ChunkItem, part, args, stats: RunStats,
    scores, chunk_qc, harness: Harness,
):
    """Device dispatch of one chunk under the robustness policy.

    Recovery ladder, applied per (sub-)chunk:

    1. **Split on OOM** — a ``RESOURCE_EXHAUSTED`` device error on a
       multi-cluster chunk halves it and re-dispatches each half through
       the one-shot path (methods are per-cluster, so outputs stay
       byte-identical), recursing down to single clusters.  Journaled as
       ``degrade`` ``action=split``.
    2. **Retry with backoff** — any transient error (I/O, device
       pressure, a watchdog-broken hang, an unsplittable OOM) re-runs
       the same dispatch up to ``--retries`` times.
    3. **Reroute to the host oracle** — when retries are exhausted on a
       still-transient DEVICE error, the chunk falls back to the numpy
       backend (the degradation the existing routing machinery applies
       statically for CPU-only gap-average, here applied dynamically).
       Journaled as ``degrade`` ``action=reroute``.
    4. **Surface** — permanent errors (malformed input) skip the ladder
       entirely and propagate to ``--on-error``.

    ``--no-degrade`` disables steps 1 and 3."""
    import time as _time

    from specpride_tpu.backends import numpy_backend as _nb

    policy = harness.policy

    def _run_parts(sub_part, prepared, attempt=0):
        while True:
            try:
                with harness.section("dispatch"):
                    if prepared is not None:
                        reps, cosines = backend.run_prepared(prepared)
                        if chunk_qc is not None and cosines is not None:
                            _append_qc_rows(chunk_qc, sub_part, cosines)
                        return reps
                    return _run_method(
                        backend, method, sub_part, args, scores=scores,
                        qc=chunk_qc,
                    )
            except Exception as e:  # noqa: BLE001 - classified ladder below
                if (
                    harness.degrade and rb_errors.is_oom(e)
                    and len(sub_part) > 1
                ):
                    harness.note_degrade(
                        "split", f"{type(e).__name__}: {e}",
                        item.index, len(sub_part),
                    )
                    logger.warning(
                        "device OOM on a %d-cluster chunk (%s); splitting "
                        "in half", len(sub_part), e,
                    )
                    mid = (len(sub_part) + 1) // 2
                    return (
                        _run_parts(sub_part[:mid], None)
                        + _run_parts(sub_part[mid:], None)
                    )
                if attempt < policy.retries and rb_errors.is_transient(e):
                    wait = policy.backoff_s("dispatch", attempt)
                    policy.note_retry("dispatch", attempt, e, wait)
                    if wait > 0:
                        _time.sleep(wait)
                    attempt += 1
                    continue
                if (
                    harness.degrade and backend is not _nb
                    and rb_errors.is_transient(e)
                ):
                    harness.note_degrade(
                        "reroute", f"{type(e).__name__}: {e}",
                        item.index, len(sub_part),
                    )
                    logger.warning(
                        "device path failed %d time(s) on a %d-cluster "
                        "chunk (%s); rerouting to the numpy backend",
                        attempt + 1, len(sub_part), e,
                    )
                    # the host fallback is the LAST resort: injection is
                    # suppressed on it (a different physical path than
                    # the device lane the fault plan models)
                    with rb_faults.suppressed():
                        return _run_method(
                            _nb, method, sub_part, args, scores=scores,
                            qc=chunk_qc,
                        )
                raise

    with stats.phase("compute"):
        return _run_parts(part, item.prepared)


def _checkpointed_run(
    backend, method, clusters, args, stats: RunStats, scores=None,
    qc: list | None = None, journal=None, quarantine: Quarantine | None = None,
    harness: Harness | None = None,
):
    """Chunked execution with a resume manifest (survey §5).

    Crash-safety contract: each chunk appends to the output FIRST, then the
    manifest records {done ids, output byte size} atomically.  A crash in
    between leaves output past the manifest's recorded size; resume
    truncates back to that offset before appending, so the re-run chunk is
    never duplicated (the advisor's r1 duplicate-append window).

    With ``--prefetch N`` (default 2) chunks flow through the pipelined
    executor (``_pipelined_chunks``): a background packer thread
    materializes and packs up to N chunks ahead while this thread
    dispatches, QCs, writes and checkpoints the current one.  Results are
    consumed in FIFO order, so the in-order append + manifest contract
    above is preserved verbatim; ``--prefetch 0`` is the serial path.
    Output is chunk-invariant (every method is per-cluster), so pipelined
    and serial runs produce byte-identical files."""
    journal = journal if journal is not None else NullJournal()
    # an elastic run passes ONE caller-owned harness across all its
    # ranges so fault-plan visit counters and retry accounting span the
    # whole rank lifetime (a per-range plan would reset AFTER offsets at
    # every range boundary); one-shot runs build and own theirs here
    owns_harness = harness is None
    if owns_harness:
        harness = Harness.from_args(args, journal)
    try:
        return _checkpointed_run_impl(
            backend, method, clusters, args, stats, scores, qc, journal,
            quarantine, harness,
        )
    finally:
        # robustness accounting rides the stats object into run_end even
        # when the run aborts mid-loop; close() disarms the global fault
        # plan and stops the watchdog so nothing leaks into the next
        # in-process invocation (tests, bench) whatever exit path ran
        # (shared harnesses re-summarize cumulatively per range and
        # close with their owner)
        rb = harness.summary(
            quarantined=quarantine.count if quarantine is not None else 0
        )
        if rb:
            stats.robustness = rb
        if owns_harness:
            harness.close()


def _checkpointed_run_impl(
    backend, method, clusters, args, stats: RunStats, scores, qc,
    journal, quarantine, harness: Harness,
):
    integ = OutputIntegrity()
    done: set[str] = set()
    output_bytes: int | None = None  # None: manifest predates offset tracking
    restarted = False  # a resume state was found unusable and discarded
    prior_failed: list[str] = []  # failures recorded by an earlier attempt
    if args.checkpoint and os.path.exists(args.checkpoint):
        manifest: dict | None = None
        try:
            with open(args.checkpoint, encoding="utf-8") as fh:
                manifest = json.load(fh)
            if not isinstance(manifest, dict):
                raise ValueError("manifest is not a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            # a torn or bit-flipped manifest (json.JSONDecodeError is a
            # ValueError): nothing in it can be trusted, so restart —
            # loudly, never by silently treating it as "no checkpoint"
            logger.warning(
                "checkpoint %s is unreadable (%s); restarting from "
                "scratch", args.checkpoint, e,
            )
            journal.emit(
                "resume_repair", action="restart",
                reason="manifest_unreadable", error=str(e),
            )
            harness.note_repair()
            done, output_bytes, restarted = set(), 0, True
        if manifest is not None:
            done = set(manifest.get("done", []))
            prior_failed = list(manifest.get("failed", []))
            raw = manifest.get("output_bytes")
            output_bytes = None if raw is None else int(raw)
            out_size = (
                os.path.getsize(args.output)
                if os.path.exists(args.output)
                else None
            )
            if done and out_size is None:
                logger.warning(
                    "checkpoint lists %d done clusters but output %s is "
                    "gone; restarting from scratch", len(done), args.output,
                )
                journal.emit(
                    "resume_repair", action="restart",
                    reason="output_missing",
                )
                harness.note_repair()
                # no output on disk -> nothing a redo could duplicate, so
                # this restart is safe even under --append
                done, output_bytes = set(), 0
                prior_failed = []  # the redo retries them; stale records lie
            elif output_bytes is not None and out_size is not None and (
                out_size < output_bytes
            ):
                # un-fsynced append lost in a power cut after the manifest
                # landed: done-listed clusters are missing from the output,
                # so trusting the manifest would silently drop them
                logger.warning(
                    "output %s is %d bytes but the manifest recorded %d; "
                    "restarting from scratch", args.output, out_size,
                    output_bytes,
                )
                journal.emit(
                    "resume_repair", action="restart",
                    reason="output_shorter_than_manifest",
                )
                harness.note_repair()
                done, output_bytes, restarted = set(), 0, True
                prior_failed = []  # the redo retries them; stale records lie
            elif output_bytes is not None and out_size is not None and (
                out_size > output_bytes
            ):
                logger.info(
                    "dropping %d output bytes past the manifest "
                    "(interrupted chunk)", out_size - output_bytes,
                )
                from specpride_tpu.io.mgf import truncate_tail

                clean = truncate_tail(args.output, output_bytes)
                journal.emit(
                    "resume_repair", action="truncate_tail",
                    reason="torn_tail",
                    n_bytes=out_size - output_bytes,
                    clean_boundary=clean,
                )
                harness.note_repair()
                if not clean and not manifest.get("sha256"):
                    # the recorded offset lands mid-record and there is no
                    # hash to prove the prefix: the manifest itself is
                    # suspect (legacy schema), so don't trust it
                    logger.warning(
                        "truncated output does not end on a record "
                        "boundary and the manifest has no sha256; "
                        "restarting from scratch",
                    )
                    journal.emit(
                        "resume_repair", action="restart",
                        reason="ragged_boundary",
                    )
                    done, output_bytes, restarted = set(), 0, True
                    prior_failed = []
            # committed-prefix verification (schema-v2 manifests): a bit
            # flip INSIDE the committed region passes every byte-count
            # check above — only the hash catches it.  The verify pass
            # doubles as the seed of this run's running hash.
            want = manifest.get("sha256")
            if done and output_bytes and os.path.exists(args.output):
                got = integ.seed_file(args.output, output_bytes)
                if want and got != want:
                    logger.warning(
                        "output %s fails the manifest's sha256 check "
                        "(committed prefix is corrupt); restarting from "
                        "scratch", args.output,
                    )
                    journal.emit(
                        "resume_repair", action="restart",
                        reason="sha256_mismatch",
                    )
                    harness.note_repair()
                    done, output_bytes, restarted = set(), 0, True
                    prior_failed = []
                    integ.reset()
        logger.info("resuming: %d clusters already done", len(done))
        journal.emit(
            "resume", n_done=len(done), restarted=restarted,
            n_prior_failed=len(prior_failed),
        )

    # index-based filtering: a StreamedClusters input exposes ids from its
    # byte index, so resume filtering never materialises member spectra
    ids = (
        clusters.cluster_ids
        if hasattr(clusters, "cluster_ids")
        else [c.cluster_id for c in clusters]
    )
    todo_idx = [i for i, cid in enumerate(ids) if cid not in done]
    resumed_ids = set(done)  # skipped THIS run (QC recomputes only these)
    stats.count("clusters_skipped_done", len(ids) - len(todo_idx))
    first_write = not done if output_bytes is None else output_bytes == 0
    if getattr(args, "append", False):
        if restarted:
            # with --append we cannot tell pre-existing user content apart
            # from this run's partial/corrupt output, so re-appending would
            # duplicate records (advisor r3): refuse rather than guess
            raise SystemExit(
                f"resume state for {args.output} is unusable (see warning "
                "above) and --append cannot safely redo on top of partial "
                f"output; remove the stale checkpoint {args.checkpoint} "
                "(and clean the output) before re-running"
            )
        # ref average_spectrum_clustering.py:183-184,198: mode 'wa'[append]
        first_write = False
    if not first_write and integ.offset == 0 and os.path.exists(args.output):
        # --append pre-existing content, or a legacy (schema-less) resume
        # the hash verify above didn't seed: fold the committed prefix
        # into the running hash so this run's manifests cover the WHOLE
        # output, not just its own appends
        integ.seed_file(
            args.output,
            output_bytes if output_bytes is not None
            else os.path.getsize(args.output),
        )
    # chunk size: the checkpoint interval, else the stream window (so a
    # streamed run stays memory-bounded even without --checkpoint), else —
    # when the pipelined executor can actually pack this method ahead —
    # the checkpoint interval anyway (one monolithic chunk would leave
    # the packer nothing to run ahead of).  Backends/paths with no pack
    # stage (numpy oracle, mesh/bucketized layouts, best-spectrum) keep
    # the old single-chunk execution: forcing small chunks there would
    # shrink device batches for zero overlap gain.
    prefetch = max(int(getattr(args, "prefetch", 0) or 0), 0)
    can_prepare = prefetch > 0 and getattr(
        backend, "supports_prepare", lambda _m: False
    )(method)
    chunk = (
        args.checkpoint_every
        if args.checkpoint
        else getattr(clusters, "window", 0)
        or (getattr(args, "checkpoint_every", 512) if can_prepare else 0)
        or len(todo_idx)
        or 1
    )

    if not todo_idx:
        # zero clusters (empty input / empty shard): still produce an
        # output file so downstream steps see a result, not ENOENT
        # (append mode opens 'a' — creates without truncating user content)
        write_mgf([], args.output, append=not first_write)

    # carry failures recorded by an interrupted earlier attempt — a resume
    # must not silently erase the record of clusters it never produced
    # (dict-as-ordered-set: a cluster failing again must not double-count)
    failed: dict[str, None] = dict.fromkeys(prior_failed)
    qc_failed: dict[str, None] = {}
    on_error = getattr(args, "on_error", "abort")
    import time as _time

    worklist = [
        (chunk_index, todo_idx[start : start + chunk])
        for chunk_index, start in enumerate(range(0, len(todo_idx), chunk))
    ]
    # the pipeline needs >= 2 chunks to overlap anything; a single-chunk
    # run takes the serial path so it never pays for a packer thread
    pipelined = prefetch > 0 and len(worklist) > 1
    pw = getattr(args, "pack_workers", None)
    n_workers = _default_pack_workers() if pw is None else max(int(pw), 0)
    lanes: dict = {"pack_busy_s": [], "reorder_stall_s": 0.0}
    if pipelined and n_workers >= 1:
        items = _pooled_chunks(
            clusters, worklist, backend, method, args, prefetch,
            qc is not None, n_workers, lanes, harness=harness,
        )
    elif pipelined:
        items = _pipelined_chunks(
            clusters, worklist, backend, method, args, prefetch,
            qc is not None, lanes, harness=harness,
        )
    else:
        items = _serial_chunks(clusters, worklist)
    h2d_slots = max(int(getattr(args, "h2d_buffer", 0) or 0), 0)
    h2d_active = (
        pipelined and h2d_slots > 0
        and getattr(backend, "supports_h2d_stage", None) is not None
        and can_prepare
    )
    if h2d_active:
        # double-buffered H2D: a transfer lane between pack and dispatch
        # device_puts chunk i+1's arguments while chunk i dispatches
        items = _h2d_staged_chunks(items, backend, h2d_slots, lanes)
    aw = getattr(args, "async_write", "auto")
    committer = (
        _Committer(
            args, journal, qc if qc is not None else [], done, first_write,
            depth=max(prefetch, 1), integrity=integ, harness=harness,
        )
        if worklist and (aw == "on" or (aw == "auto" and pipelined))
        else None
    )
    idle_s = 0.0
    loop_t0 = _time.perf_counter()

    clip_fn = getattr(args, "_elastic_clip", None)
    rc = getattr(args, "_result_cache", None)
    try:
        for item in items:
            if clip_fn is not None and item.idxs:
                # elastic tier 2: before dispatching this chunk, let the
                # coordinator ratify a pending steal proposal at THIS
                # boundary (everything already submitted commits below
                # it) or report an existing cut.  Chunks at/past the cut
                # belong to the stealing rank now — stop dispatching.
                clip = clip_fn(item.idxs[0])
                if clip is not None and item.idxs[0] >= clip:
                    logger.info(
                        "range split: stopping before local cluster %d "
                        "(%d chunk(s) ceded to the stealing rank)",
                        clip, len(worklist) - item.index,
                    )
                    if hasattr(items, "close"):
                        items.close()  # shut the pack lanes promptly
                    break
            chunk_index, part = item.index, item.part
            idle_s += item.wait_s
            if item.pack_stats is not None:
                # packer-thread time lands in the run's `pack` phase (NOT in
                # the consumer's compute wall time), so the phase report and
                # the compute+write throughput stay truthful under prefetch
                stats.merge(item.pack_stats)
            journal.emit(
                "chunk_start", chunk_index=chunk_index, n_clusters=len(item.idxs)
            )
            # the per-chunk span is the trace's unit of progress: everything a
            # chunk does (compute, QC, write, checkpoint) nests under it, so a
            # straggler chunk is visible as one long slice on the timeline
            # (closed in the finally — an abort mid-chunk must not leak an
            # open span onto the tracer's per-thread stack)
            chunk_span = tracing.span(
                "chunk", chunk_index=chunk_index, n_clusters=len(item.idxs)
            )
            chunk_span.__enter__()
            try:
                chunk_t0 = _time.perf_counter()
                # per-chunk QC rows buffer: rows land in the shared report
                # list only at commit time (inline below, or on the write
                # lane), so the committer can own "QC finalize" without the
                # dispatch lane ever racing it on the list
                chunk_qc: list | None = [] if qc is not None else None
                # result cache: the pack lane consulted already when
                # pipelined; the serial path consults here.  miss_part
                # is what actually computes — hits replay straight into
                # the commit tail below.
                if rc is not None and item.cached is None and \
                        item.error is None and part is not None:
                    item.cached = rc.consult(part)
                cached = item.cached
                hit_ids = rc.hit_ids(cached) if rc is not None else set()
                miss_part = (
                    [c for c in part if c.cluster_id not in hit_ids]
                    if part is not None and hit_ids else part
                )
                try:
                    if item.error is not None:
                        # a pack-stage failure surfaces here so --on-error
                        # keeps one policy for the whole chunk lifecycle
                        # (transient pack errors were already retried on
                        # the pack lane; what arrives is permanent)
                        raise item.error
                    if miss_part:
                        reps = _dispatch_chunk(
                            backend, method, item, miss_part, args, stats,
                            scores, chunk_qc, harness,
                        )
                    else:
                        reps = []  # every cluster was a cache hit
                except (ValueError, RuntimeError, OSError) as e:
                    # OSError joins the policy catch so a persistent I/O
                    # failure that exhausted its retries (incl.
                    # LaneHangError, a TimeoutError->OSError subclass)
                    # follows the same skip path as a compute failure
                    # instead of aborting the run
                    # per-chunk failure isolation (survey §5 failure
                    # detection): with --on-error skip, a chunk whose input is
                    # bad (e.g. mixed charge states) is retried
                    # cluster-by-cluster so only the offending clusters are
                    # dropped — logged and recorded in the manifest, never
                    # silently
                    if on_error != "skip":
                        raise
                    if part is None:
                        # the packer died while materializing this chunk; the
                        # serial retry below needs the clusters themselves
                        part = [clusters[i] for i in item.idxs]
                        miss_part = part
                    logger.warning(
                        "chunk of %d clusters failed (%s); retrying one by one",
                        len(miss_part), e,
                    )
                    reps, bad_part = [], []
                    with stats.phase("compute"):
                        for c in miss_part:
                            try:
                                reps.extend(
                                    _run_method(
                                        backend, method, [c], args,
                                        scores=scores, qc=chunk_qc,
                                    )
                                )
                            except (ValueError, RuntimeError, OSError) as ce:
                                logger.warning(
                                    "skipping cluster %s: %s", c.cluster_id, ce
                                )
                                bad_part.append(c.cluster_id)
                    failed.update(dict.fromkeys(bad_part))
                    stats.count("clusters_failed", len(bad_part))
                if chunk_qc is not None and not chunk_qc and reps:
                    # ONE QC site for every non-fused method (the fused
                    # bin-mean path appends inside _run_method, detected by
                    # the buffer staying empty): align reps to clusters by id
                    # — best-spectrum may drop scoreless clusters — and never
                    # let a QC failure veto the representatives the method
                    # already produced.  The cosine COMPUTE stays on this
                    # lane (it may dispatch to the device); only the finished
                    # rows ride to the committer.
                    try:
                        by_id = {r.cluster_id: r for r in reps}
                        kept = [
                            c for c in miss_part if c.cluster_id in by_id
                        ]

                        def _qc_pass(kept=kept, by_id=by_id):
                            with stats.phase("compute"), tracing.span(
                                "qc", n_clusters=len(kept)
                            ):
                                rb_faults.check("qc")
                                return _cosines_of(
                                    backend,
                                    [by_id[c.cluster_id] for c in kept],
                                    kept, _cosine_config(args),
                                )

                        # transient QC failures retry like any lane; what
                        # survives the budget is handled below (rows
                        # omitted, representatives kept) — OSError joins
                        # the catch so an exhausted retry degrades the
                        # report instead of aborting the run
                        _append_qc_rows(
                            chunk_qc, kept,
                            harness.retry_call("qc", _qc_pass),
                        )
                    except (ValueError, RuntimeError, OSError) as e:
                        logger.warning(
                            "QC cosines failed for a %d-cluster chunk (%s); "
                            "their rows are omitted from the report",
                            len(miss_part), e,
                        )
                        # machine-readable trace for the report summary:
                        # consumers must be able to tell "row dropped by the
                        # method" from "QC itself failed" (advisor r4)
                        qc_failed.update(
                            dict.fromkeys(c.cluster_id for c in miss_part)
                        )
                        journal.emit(
                            "qc_failure",
                            cluster_ids=[c.cluster_id for c in miss_part],
                            error=str(e),
                        )
                populate = None
                if rc is not None and part is not None:
                    # cosines for the freshly computed clusters, so the
                    # populated entries under a QC-on key always carry
                    # the QC verdict a future hit will replay
                    qc_by_id = (
                        {row["cluster_id"]: row["avg_cosine"]
                         for row in chunk_qc}
                        if chunk_qc is not None else None
                    )
                    got = {r.cluster_id: r for r in reps}
                    populate = []
                    for c in miss_part:
                        r = got.get(c.cluster_id)
                        if r is None:
                            continue  # dropped by the method / skipped
                        cos = None
                        if qc_by_id is not None:
                            cos = qc_by_id.get(c.cluster_id)
                            if cos is None:
                                continue  # QC failed: no partial entry
                        key = (cached or {}).get(c.cluster_id)
                        populate.append((
                            key[2] if key is not None else rc.key_of(c),
                            r, c, cos,
                        ))
                    if hit_ids:
                        # scatter the stored representatives (and their
                        # QC rows) into the commit tail at their input
                        # positions — the report writer re-sorts by
                        # input order, so the bytes match cache-off
                        reps = [
                            cached[c.cluster_id][0]
                            if c.cluster_id in hit_ids
                            else got[c.cluster_id]
                            for c in part
                            if c.cluster_id in hit_ids
                            or c.cluster_id in got
                        ]
                        if chunk_qc is not None:
                            for c in part:
                                if c.cluster_id not in hit_ids:
                                    continue
                                cos = cached[c.cluster_id][1]
                                if cos is not None:
                                    chunk_qc.append({
                                        "cluster_id": c.cluster_id,
                                        "n_members": c.n_members,
                                        "avg_cosine": float(cos),
                                    })
                commit_item = _CommitItem(
                    chunk_index, reps, [c.cluster_id for c in part],
                    chunk_qc, sorted(failed) if failed else None, chunk_t0,
                    max_idx=item.idxs[-1] if item.idxs else None,
                )
                commit_item.populate = populate
                if committer is not None:
                    # ordered write lane: the whole commit tail (QC finalize,
                    # MGF append, manifest replace, chunk_done heartbeat)
                    # runs on the committer thread, FIFO.  Everything the
                    # protocol needs is snapshotted here so the manifest
                    # bytes match a serial run's exactly.
                    committer.submit(commit_item)
                else:
                    _commit_chunk(
                        commit_item, args, journal, stats,
                        qc if qc is not None else [], done, first_write,
                        integrity=integ, harness=harness,
                    )
                    first_write = False
            finally:
                chunk_span.__exit__(None, None, None)
        if committer is not None:
            # flush queued commits before the pipeline wall/lane summary so
            # write-lane time is inside the measured wall (and the output +
            # manifest are complete before the QC report re-reads them);
            # re-raises a commit error on this lane
            committer.finish(stats)
    finally:
        close = getattr(items, "close", None)
        if close is not None:
            # stop the pack lanes NOW on a dispatch-lane abort — an
            # un-closed generator would only run its cleanup (stop +
            # join) whenever the traceback gets collected, leaving
            # workers parked on the admit gate holding packed chunks
            close()
        if committer is not None:
            # a dispatch-lane abort must not leak the committer
            # thread; shutdown flushes chunks already queued (a
            # serial run would have written them before the
            # failing chunk too) and folds their counters in
            committer.shutdown(stats)
    if pipelined or committer is not None:
        # device_idle_s: time the dispatch lane sat starved waiting on the
        # pack lane — the overlap shortfall.  Journaled in run_end (and
        # surfaced by `specpride stats`) so the pipeline's win/loss is
        # measurable per run: overlap_efficiency = 1 - idle / wall.
        # Per-lane busy seconds and the reorder-buffer stall time make
        # the three lanes' load visible without opening a trace.
        wall = _time.perf_counter() - loop_t0
        stats.pipeline = {
            "prefetch": prefetch,
            # the EFFECTIVE pool size: _pooled_chunks clamps to the chunk
            # count, and the per-worker busy list must match it (0 = the
            # dedicated single packer / no pipeline)
            "pack_workers": (
                len(lanes["pack_busy_s"])
                if pipelined and n_workers >= 1 else 0
            ),
            "async_write": committer is not None,
            "n_chunks": len(worklist),
            "device_idle_s": round(idle_s, 4),
            "wall_s": round(wall, 4),
            "overlap_efficiency": (
                round(1.0 - idle_s / wall, 4) if wall > 0 else None
            ),
            "pack_busy_s": [round(b, 4) for b in lanes["pack_busy_s"]],
            "write_busy_s": (
                round(committer.busy_s, 4) if committer is not None else 0.0
            ),
            "reorder_stall_s": round(lanes["reorder_stall_s"], 4),
        }
        if h2d_active:
            # H2D transfer-lane summary: staged bytes/busy time, the
            # dispatch-lane starvation attributable to staging (total
            # starvation minus what the lane itself spent waiting on
            # the pack lane), and the hidden-transfer fraction
            h2d_busy = lanes["h2d_busy_s"][0]
            h2d_stall = max(
                0.0, idle_s - lanes["h2d_upstream_wait_s"][0]
            )
            stats.pipeline["h2d"] = {
                "slots": h2d_slots,
                "busy_s": round(h2d_busy, 4),
                "bytes": int(lanes["h2d_bytes"][0]),
                "stall_s": round(h2d_stall, 4),
                "overlap_efficiency": (
                    round(
                        max(0.0, 1.0 - h2d_stall / h2d_busy), 4
                    ) if h2d_busy > 0 else 1.0
                ),
            }
    if failed:
        logger.warning(
            "%d clusters failed and were skipped: %s%s",
            len(failed), ", ".join(list(failed)[:5]),
            "..." if len(failed) > 5 else "",
        )
        # the warning truncates at 5; the journal carries the FULL list so
        # an --on-error skip run stays auditable without log archaeology
        journal.emit("skipped_clusters", cluster_ids=sorted(failed))
    return resumed_ids, list(failed), list(qc_failed)


# eager-load ceiling for --stream-clusters auto: above this input size the
# CLI switches to windowed streaming so host RAM stops capping input size
_STREAM_AUTO_BYTES = 256 * 1024 * 1024


def _load_clusters_served(args, stats: RunStats, quarantine):
    """Serving lanes only: consult the daemon's parsed-input residency
    (``serve.ingest_cache``) before paying the parse — repeat jobs over
    an unchanged input are THE serving scenario, and on hosts without
    the native parser the Python parse is the largest GIL-bound slice
    of a warm job (it caps what concurrent lanes can overlap).  Only
    eager, quarantine-free parses are eligible; everything else (and
    every one-shot CLI run) takes ``_load_clusters`` untouched."""
    mode = (getattr(args, "stream_clusters", "off") or "off").lower()
    eager = mode == "off" or (
        mode == "auto"
        and os.path.exists(args.input)
        and os.path.getsize(args.input) < _STREAM_AUTO_BYTES
    )
    cacheable = (
        getattr(args, "_serve_worker", None) is not None
        and quarantine is None
        and eager
        and not args.input.endswith(".gz")
    )
    if cacheable:
        from specpride_tpu.serve import ingest_cache

        got, kind = ingest_cache.lookup(args.input)
        if got is not None:
            clusters, n_spectra, n_peaks = got
            stats.count("spectra_in", n_spectra)
            stats.count("peaks_in", n_peaks)
            stats.count("ingest_cache_hits", 1)
            if kind == "content":
                # same bytes under a new stat identity: still a skipped
                # parse, but attributed separately so operators can see
                # the fallback working
                stats.count("ingest_cache_content_hits", 1)
            return clusters
    clusters = _load_clusters(
        args.input, stats, getattr(args, "stream_clusters", "off"),
        quarantine=quarantine,
    )
    if cacheable and isinstance(clusters, list):
        from specpride_tpu.serve import ingest_cache

        stats.count("ingest_cache_misses", 1)
        ingest_cache.put(
            args.input, clusters,
            n_spectra=stats.counters.get("spectra_in", 0),
            n_peaks=stats.counters.get("peaks_in", 0),
        )
    return clusters


def _load_clusters(path: str, stats: RunStats, stream: str = "off",
                   quarantine: Quarantine | None = None):
    """Clusters from a clustered MGF: eager list, or a bounded-memory
    ``StreamedClusters`` view (``--stream-clusters``: "off", "auto" = only
    for inputs over 256 MB, or an explicit window size in clusters).
    Streaming needs a plain (non-gz) file; otherwise it falls back to
    eager with a warning.

    With a ``quarantine`` (armed by ``--on-error skip``) malformed MGF
    blocks — truncated records, unparseable peak lines — divert to
    ``<output>.quarantine.mgf`` instead of aborting: eager reads parse
    tolerantly (Python parser; the C++ fast path fails hard on damage),
    streamed reads quarantine both the index scan's truncated spans and
    any record a window parse rejects."""
    mode = (stream or "off").lower()
    window = 0
    if mode not in ("off", "auto"):
        window = int(mode)
    eager = window <= 0 and (
        mode == "off"
        or os.path.getsize(path) < _STREAM_AUTO_BYTES
    )
    if not eager and path.endswith(".gz"):
        logger.warning(
            "--stream-clusters needs a plain MGF (gz has no byte index); "
            "loading eagerly"
        )
        eager = True

    # explicit opt-in site for the C++ fast parser: the CLI (unlike
    # library reads) may spawn the one-shot in-tree build
    from specpride_tpu.io import native

    native.ensure_built()
    if eager:
        with stats.phase("parse"):
            spectra = read_mgf(
                path,
                malformed=quarantine.add if quarantine is not None else None,
            )
            clusters = group_into_clusters(spectra)
        stats.count("spectra_in", len(spectra))
        stats.count("peaks_in", sum(s.n_peaks for s in spectra))
        return clusters

    from specpride_tpu.io.mgf import StreamedClusters

    with stats.phase("parse"):
        clusters = StreamedClusters(path, window=window or 512)
    if quarantine is not None:
        # window parses (pack lane, possibly several workers) quarantine
        # per-record damage; the index scan's truncated spans drain once
        # here — without the quarantine both were silently dropped
        clusters.on_malformed = quarantine.add
        clusters.drain_malformed(quarantine.add)
    logger.info(
        "streaming %d clusters (%d spectra) in windows of %d",
        len(clusters), clusters.n_spectra, clusters.window,
    )
    stats.count("spectra_in", clusters.n_spectra)
    return clusters


def _is_mzml(path: str) -> bool:
    return path.lower().endswith((".mzml", ".mzml.gz"))


def _clusters_from_mzml(path: str, args, stats: RunStats) -> list[Cluster]:
    """Direct mzML + MaRaCluster ingestion — the reference's C1 entry that
    needs no pre-conversion step (ref src/binning.py:33-118: read the
    cluster list, read exactly the clustered scans, group): titles become
    ``cluster;usi`` on the fly, with peptide interpretations when --msms
    is given (optional, as in the reference)."""
    from specpride_tpu.data.peaks import build_title
    from specpride_tpu.io.maracluster import scan_to_cluster
    from specpride_tpu.io.maxquant import read_msms_peptides
    from specpride_tpu.io.mzml import read_mzml_scans

    if not getattr(args, "clusters", None):
        raise SystemExit(
            "an .mzML input needs --clusters <MaRaCluster TSV> (or run "
            "`specpride convert` first)"
        )
    with stats.phase("parse"):
        cluster_of = scan_to_cluster(args.clusters)
        spectra = read_mzml_scans(path, scans=set(cluster_of))
        peptides = (
            read_msms_peptides(args.msms)
            if getattr(args, "msms", None)
            else {}
        )
    raw = (
        getattr(args, "raw_name", None)
        or os.path.basename(path).split(".")[0]
    )
    px = getattr(args, "px_accession", "PXD004732")
    out = []
    for scan in sorted(spectra):
        s = spectra[scan]
        s.title = build_title(
            cluster_of[scan], px, raw, scan, peptides.get(scan),
            s.precursor_charge if peptides.get(scan) else None,
        )
        out.append(s)
    stats.count("spectra_in", len(out))
    return group_into_clusters(out)


# clusters re-verified against the f32 oracle per reduced-precision run:
# bounded so the gate stays a fixed cost however large the input is (the
# per-cluster property being validated — quantization drift under THIS
# config on THIS data distribution — is i.i.d. across clusters)
_PRECISION_GATE_SAMPLE = 32


def _precision_gate(args, backend, clusters, method, stats, journal):
    """QC-cosine tolerance gate for reduced-precision runs: recompute a
    deterministic sample of clusters at the run's precision AND at f32,
    and require every pair's binned cosine to clear the documented
    per-(method, precision) tolerance (``ops.quantize.
    precision_tolerance``).  f32 runs skip — they ARE the oracle.

    The gate runs on twin backends with private telemetry so its
    dispatches never pollute the run's own byte/compile accounting
    (the CI precision pass compares journaled h2d_bytes across
    precisions).  Results land in ``run_end.precision``; a breach
    journals first, then aborts the run with a nonzero exit — a
    reduced-precision output that cannot demonstrate fidelity on its
    own data must not pass silently."""
    import dataclasses as _dc

    precision = getattr(backend, "precision", "f32")
    if precision == "f32":
        return
    if method not in ("bin-mean", "gap-average", "medoid"):
        stats.precision = {"precision": precision, "gated": False}
        return
    if not _dc.is_dataclass(type(backend)):
        # a batched member job runs against the batcher's read-only
        # result view (serve.BatchResultBackend), which forwards the
        # resident backend's precision but cannot be twinned; fidelity
        # of the shared dispatch is gated by the daemon's solo jobs on
        # the same backend — record, don't crash
        stats.precision = {
            "precision": precision, "gated": False,
            "reason": "shared-batch-member",
        }
        return

    from specpride_tpu.backends import numpy_backend as _nb
    from specpride_tpu.ops.quantize import precision_tolerance

    n = min(len(clusters), _PRECISION_GATE_SAMPLE)
    sample = [
        c for c in (clusters[i] for i in range(n)) if c.n_members > 0
    ]
    tol = precision_tolerance(method, precision)
    if not sample:
        stats.precision = {
            "precision": precision, "gated": False, "tolerance": tol,
        }
        return

    def _twin(prec: str):
        return _dc.replace(
            backend, precision=prec, stats=RunStats(),
            metrics=MetricsRegistry(), journal=NullJournal(),
            _seen_shapes=set(), _routing_noted=set(),
            _precision_noted=set(),
        )

    cfg = _method_config(method, args)
    ccfg = _cosine_config(args)
    with stats.phase("compute"), tracing.span(
        "precision_gate", n_clusters=len(sample), precision=precision,
    ):
        if method == "medoid":
            red = _twin(precision).medoid_indices(sample, cfg)
            ref = _twin("f32").medoid_indices(sample, cfg)
            cosines = [
                1.0 if a == b else _nb.binned_cosine(
                    c.members[a], c.members[b], ccfg
                )
                for a, b, c in zip(red, ref, sample)
            ]
        elif method == "bin-mean":
            red = _twin(precision).run_bin_mean(sample, cfg)
            ref = _twin("f32").run_bin_mean(sample, cfg)
            cosines = [
                _nb.binned_cosine(a, b, ccfg) for a, b in zip(red, ref)
            ]
        else:
            red = _twin(precision).run_gap_average(sample, cfg)
            ref = _twin("f32").run_gap_average(sample, cfg)
            cosines = [
                _nb.binned_cosine(a, b, ccfg) for a, b in zip(red, ref)
            ]
    min_cos = float(min(cosines)) if cosines else 1.0
    ok = min_cos >= tol
    result = {
        "precision": precision,
        "gated": True,
        "checked": len(sample),
        "min_cosine": round(min_cos, 6),
        "mean_cosine": round(sum(cosines) / len(cosines), 6),
        "tolerance": tol,
        "ok": ok,
    }
    stats.precision = result
    journal.emit("precision", method=method, **result)
    if not ok:
        raise SystemExit(
            f"precision gate FAILED: {method} at --precision {precision} "
            f"scored min cosine {min_cos:.6f} vs the f32 oracle over "
            f"{len(sample)} sampled clusters (tolerance {tol}); rerun at "
            "f32 or a wider tolerance precision"
        )
    logger.info(
        "precision gate: %s %s min_cosine=%.6f >= %.4g over %d clusters",
        method, precision, min_cos, tol, len(sample),
    )


def _warmup_manifest_path(args) -> str | None:
    """The shape-manifest path this run reads/writes: the explicit
    ``--warmup-manifest``, else the default beside the compile cache
    (a manifest indexes what the cache next to it holds)."""
    explicit = getattr(args, "warmup_manifest", None)
    if explicit:
        return explicit
    from specpride_tpu.warmstart import cache as ws_cache
    from specpride_tpu.warmstart.manifest import DEFAULT_BASENAME

    state = ws_cache.cache_state()
    if state.enabled and state.dir:
        return os.path.join(state.dir, DEFAULT_BASENAME)
    return None


# kernel-name prefixes each method can dispatch: a per-run auto warmup
# warms only what THIS run can use (the cosine kernels serve every
# method's --qc-report); `specpride warmup` still warms a whole
# manifest — that is the serve-everything daemon/boot path
_METHOD_KERNEL_PREFIXES = {
    "bin-mean": ("bin_mean", "cosine_"),
    "gap-average": ("gap_average", "cosine_"),
    "medoid": ("medoid_", "shared_bins", "cosine_"),
    "best": ("cosine_",),
}

# per-run auto-warmup ceiling: shape classes are bounded by design
# (pow2/half-octave size classes), but a long-lived shared manifest
# unions every workload ever run — cap the per-run pass and log the
# rest rather than let startup cost grow without bound
_WARMUP_MAX_ENTRIES = 64


def _run_warmup(args, backend, journal) -> None:
    """``--warmup``: AOT-compile every manifest shape class concurrently
    BEFORE the pack lane starts, so the chunk loop never stalls on an
    XLA compile (each variant either compiles once into the persistent
    cache or loads from it; per-kernel outcome journaled as warmup
    events)."""
    mode = getattr(args, "warmup", "auto")
    if mode == "off" or not hasattr(backend, "_seen_shapes"):
        return  # disabled, or the numpy oracle (nothing to compile)
    if getattr(args, "_resident_warm", False):
        # serving daemon: boot already AOT-warmed the manifest and the
        # resident backend's jit caches hold everything since — a
        # per-request re-warm would re-lower every manifest entry for
        # nothing.  (Manifest SAVING still runs: jobs seed future boots.)
        return
    path = _warmup_manifest_path(args)
    exists = path is not None and os.path.exists(path)
    if mode == "manifest" and not exists:
        raise SystemExit(
            "--warmup manifest: no shape manifest at "
            f"{path or '<no --warmup-manifest and no compile cache>'} "
            "(run the workload once with --warmup auto, or point "
            "--warmup-manifest at a saved one)"
        )
    if not exists:
        return  # auto: nothing recorded yet — this run will seed it
    from specpride_tpu.warmstart.manifest import load_manifest
    from specpride_tpu.warmstart.warmup import warm_entries

    try:
        entries = load_manifest(path)
    except (OSError, ValueError) as e:
        if mode == "manifest":
            raise SystemExit(f"unreadable shape manifest {path}: {e}")
        logger.warning("ignoring shape manifest %s (%s)", path, e)
        return
    prefixes = _METHOD_KERNEL_PREFIXES.get(args.method)
    if prefixes is not None:
        kept = [e for e in entries if e.kernel.startswith(prefixes)]
        if len(kept) < len(entries):
            logger.info(
                "warmup: %d of %d manifest entries apply to --method %s",
                len(kept), len(entries), args.method,
            )
        entries = kept
    if len(entries) > _WARMUP_MAX_ENTRIES:
        # manifests only grow (merge_manifest unions); a shared default
        # cache accumulating many workloads' shape classes must not turn
        # every run's startup into an unbounded compile pass.  Never a
        # silent cap: the skip is logged, and `specpride warmup` (no
        # cap) remains the warm-everything path.
        logger.warning(
            "warmup: manifest has %d entries for this method; warming "
            "the first %d (run `specpride warmup %s` to warm them all)",
            len(entries), _WARMUP_MAX_ENTRIES, path,
        )
        entries = entries[:_WARMUP_MAX_ENTRIES]
    warm_entries(
        entries, journal=journal,
        # warm the jit twin the run will actually dispatch (donation
        # resolves off on cpu-only hosts — the backend knows)
        donate=getattr(backend, "_donate_effective", False),
    )


# concurrent serving lanes finish jobs (and therefore merge shape
# manifests) concurrently; merge_manifest is read-modify-replace, so
# without mutual exclusion one lane's entries could vanish under a
# last-writer-wins race
_manifest_lock = threading.Lock()


def _save_shape_manifest(args, backend) -> None:
    """Persist the (kernel, shape-class) set this run dispatched into
    the shape manifest, so the NEXT process can warm up before its first
    chunk.  No-op with ``--warmup off`` or without a manifest home."""
    if getattr(args, "warmup", "auto") == "off":
        return
    seen = getattr(backend, "_seen_shapes", None)
    if not seen:
        return  # numpy backend, or a run that never dispatched
    snapshot = getattr(args, "_shapes_snapshot", None)
    if snapshot:
        # multi-job processes (the serving daemon): persist only THIS
        # run's new shape classes.  Re-persisting another job's shapes
        # under this job's method config would mint spurious
        # (shape, config) manifest entries no dispatch ever performs —
        # entries a later warmup would then compile for nothing.
        seen = set(seen) - snapshot
        if not seen:
            return
    path = _warmup_manifest_path(args)
    if path is None:
        return
    from specpride_tpu.warmstart.manifest import (
        entries_from_seen,
        merge_manifest,
    )

    entries = entries_from_seen(seen, _method_config(args.method, args))
    if not entries:
        return
    try:
        with _manifest_lock:
            n = merge_manifest(path, entries)
    except (OSError, ValueError) as e:
        logger.warning("could not update shape manifest %s (%s)", path, e)
        return
    logger.info("shape manifest: %d shape class(es) -> %s", n, path)


_TRACER_UNSET = object()


def _install_tracer_early(args) -> None:
    """Install the span tracer BEFORE input parsing so the parse phase —
    often the largest — is on the timeline too (the acceptance bar is
    spans covering >=95% of phase-timer time).  Parse-time spans buffer
    in memory until ``_open_run_journal`` attaches the journal and
    replays them.  Callers must pair this with ``_restore_tracer`` in a
    ``finally`` — an early exit (bad input, SystemExit) must not leak a
    process-global tracer.

    Served jobs (``args._serve_worker`` set by the daemon's worker pool)
    install THREAD-locally instead: concurrent lanes each trace their
    own job, and a job's spans can never land in a neighbour's journal
    (the lane threads a run spawns adopt the installing thread's
    tracer)."""
    chrome = getattr(args, "chrome_trace", None)
    if getattr(args, "journal", None) or chrome:
        args._prev_tracer = _set_run_tracer(args, Tracer(keep=True))


def _set_run_tracer(args, tracer):
    """Install a run's tracer in the right scope: thread-local on a
    serving worker lane, process-global for one-shot runs."""
    if getattr(args, "_serve_worker", None) is not None:
        args._tracer_thread = True
        return tracing.set_thread_current(tracer)
    return tracing.set_current(tracer)


def _restore_tracer(args) -> None:
    """Restore the tracer saved by ``_install_tracer_early`` /
    ``_open_run_journal``.  Idempotent: ``_finish_run`` restores on the
    happy path; the command's ``finally`` catches every early exit."""
    prev = args.__dict__.pop("_prev_tracer", _TRACER_UNSET)
    if prev is not _TRACER_UNSET:
        if getattr(args, "_tracer_thread", False):
            tracing.set_thread_current(prev)
        else:
            tracing.set_current(prev)


def _open_run_journal(args, backend, command: str, n_clusters: int):
    """Open the --journal stream (NullJournal when absent), hook it into
    the backend's dispatch instrumentation, install the span tracer
    (journal-fed and/or in-memory for ``--chrome-trace``), and emit
    ``run_start``."""
    journal = open_journal(getattr(args, "journal", None))
    if hasattr(backend, "journal"):  # TpuBackend; the numpy module has none
        backend.journal = journal
        # --metrics-out without --journal must still pay for pack-waste
        # accounting: its padding gauges come from the same counters
        if getattr(args, "metrics_out", None):
            backend.pack_accounting = True
    # the v4 causal envelope: adopt the context a parent hop handed us
    # (the serving daemon via args, a fleet supervisor via the
    # SPECPRIDE_TRACE env) or mint a fresh trace — either way every
    # event this journal emits carries the trace_id, and the clock
    # anchor ties this process's mono axis to the wall clock FIRST so
    # the trace merger can place everything that follows
    ctx = getattr(args, "_trace_ctx", None) or TraceContext.from_env()
    if ctx is None:
        ctx = TraceContext.mint()
    args._trace_ctx = ctx
    journal.bind_trace(ctx.trace_id)
    journal.emit(
        "run_start", command=command,
        method=getattr(args, "method", command),
        backend=getattr(args, "backend", "numpy"),
        n_clusters=int(n_clusters), output=args.output,
    )
    if journal.enabled:
        # directly after run_start so the anchor lands in THIS run's
        # segment (the merger fits clocks per run_start segment)
        emit_clock_anchor(journal)
    if hasattr(backend, "journal"):
        # device runs: record how the persistent compilation cache
        # resolved (dir, or why it stayed off) and snapshot the
        # hit/miss counters so run_end can report this run's delta —
        # post-mortems must be able to tell cached from cold runs
        from specpride_tpu.warmstart import cache as ws_cache

        state = ws_cache.cache_state()
        journal.emit(
            "compile_cache", enabled=state.enabled, dir=state.dir,
            reason=state.reason, source=state.source,
        )
        served = getattr(args, "_serve_worker", None) is not None
        if served:
            # a serving worker lane: the process-wide counters would
            # cross-attribute between jobs compiling on CONCURRENT
            # lanes.  Every compile a job causes fires on its worker
            # thread (dispatch, QC — the pack lanes never compile), so
            # the thread-scoped counters are exactly this job's.
            args._cc_thread_scope = True
            args._cc_snapshot = ws_cache.thread_counters_snapshot()
        else:
            args._cc_snapshot = ws_cache.counters_snapshot()
        # per-run deltas for the OTHER process-wide singletons a
        # long-lived multi-job process (the serving daemon) accumulates
        # across jobs: the bucket-plan cache counters and the backend's
        # seen-shape set.  Snapshot here, diff in _finish_run — never a
        # reset, which would zero a concurrent consumer's accounting.
        from specpride_tpu.data.packed import (
            PlanCacheScope,
            plan_cache_info,
            set_plan_scope,
        )

        if served:
            # per-job plan-cache scope: packs run on this thread AND the
            # job's pack-worker threads, which adopt the scope at thread
            # start — so the job's run_end counts its own pack traffic,
            # not a concurrent neighbour's
            args._plan_scope = PlanCacheScope()
            set_plan_scope(args._plan_scope)
        else:
            args._plan_snapshot = plan_cache_info()
        args._shapes_snapshot = set(backend._seen_shapes)
        # the backend's metrics registry is ALSO a process-wide singleton
        # in a serving daemon (kept resident so the live /metrics
        # exporter serves monotone Prometheus counters): snapshot its
        # device counters so run_end.device reports THIS job's traffic,
        # not the daemon's cumulative total
        args._device_snapshot = device_counters_snapshot(backend.metrics)
    chrome = getattr(args, "chrome_trace", None)
    if journal.enabled or chrome:
        # spans ride the SAME journal stream as the v1 events; kept in
        # memory only when a direct --chrome-trace export needs them.
        # The previous tracer is restored by _finish_run (or the
        # command's finally), so a nested cli_main (bench.py's
        # end-to-end section) cannot clobber its caller's tracer.
        if hasattr(args, "_prev_tracer"):
            # _install_tracer_early already traced the parse phase: its
            # buffered spans replay into the journal here (after
            # run_start, so journal consumers see a well-ordered run;
            # each keeps its original `mono`, so the timeline is exact).
            # The trace context lands now (the journal did not exist at
            # install time): parse-phase spans predate it and carry no
            # span ids, every span from here on does.
            tracer = tracing.current()
            tracer.ctx = ctx
            tracer.attach_journal(journal, keep=bool(chrome))
        else:
            args._prev_tracer = _set_run_tracer(
                args, Tracer(journal=journal, keep=bool(chrome), ctx=ctx)
            )
    return journal


def _finish_run(args, backend, stats: RunStats, journal) -> None:
    """Emit ``run_end`` (full summary + the device-telemetry dict both
    backends share), write the Chrome trace and the Prometheus textfile
    if requested, and uninstall the run's tracer."""
    device = device_summary(
        getattr(backend, "metrics", None),
        since=args.__dict__.pop("_device_snapshot", None),
    )
    cc_snapshot = args.__dict__.pop("_cc_snapshot", None)
    if cc_snapshot is not None:
        from specpride_tpu.warmstart import cache as ws_cache

        compile_cache = (
            ws_cache.thread_counters_delta(cc_snapshot)
            if args.__dict__.pop("_cc_thread_scope", False)
            else ws_cache.counters_delta(cc_snapshot)
        )
    else:
        compile_cache = None
    plan_scope = args.__dict__.pop("_plan_scope", None)
    plan_snapshot = args.__dict__.pop("_plan_snapshot", None)
    if plan_scope is not None:
        from specpride_tpu.data.packed import set_plan_scope

        plan_cache = plan_scope.delta()
        set_plan_scope(None)  # the lane thread outlives the job
    elif plan_snapshot is not None:
        from specpride_tpu.data.packed import plan_cache_delta

        plan_cache = plan_cache_delta(plan_snapshot)
    else:
        plan_cache = None
    shapes_snapshot = args.__dict__.pop("_shapes_snapshot", None)
    if shapes_snapshot is not None:
        seen = getattr(backend, "_seen_shapes", set())
        shape_classes = {
            "new": len(seen - shapes_snapshot), "total": len(seen),
        }
    else:
        shape_classes = None
    rc = args.__dict__.pop("_result_cache", None)
    if rc is not None:
        # per-run cache accounting: its own additive event (cache-off
        # journals stay byte-identical by absence) AND counters folded
        # into run_end so job summaries / job_done attribution see hits
        # without re-reading the journal
        rc_snap = rc.snapshot()
        journal.emit(
            "result_cache",
            hits=rc_snap["hits"], misses=rc_snap["misses"],
            populated=rc_snap["populated"],
            evictions=rc_snap["evictions"],
            bytes_saved=rc_snap["bytes_saved"],
            shared_hits=rc_snap["shared_hits"],
            corrupt=rc_snap["corrupt"],
            entries=rc_snap["entries"], bytes=rc_snap["bytes"],
        )
        stats.count("result_cache_hits", rc_snap["hits"])
        stats.count("result_cache_misses", rc_snap["misses"])
    journal.emit(
        "run_end",
        counters=dict(stats.counters),
        phases_s={k: round(v, 4) for k, v in stats.phases.items()},
        elapsed_s=round(stats.elapsed, 4),
        representatives_written=stats.counters.get("representatives", 0),
        clusters_per_sec=round(stats.throughput("clusters"), 2),
        device=device,
        # pipelined executor summary (absent on serial runs): prefetch
        # depth, device_idle_s, overlap_efficiency — see _checkpointed_run
        **({"pipeline": stats.pipeline} if getattr(
            stats, "pipeline", None
        ) else {}),
        # robustness summary (absent when the layer stayed dormant):
        # injected faults, retries, degrades, repairs, quarantined blocks
        **({"robustness": stats.robustness} if getattr(
            stats, "robustness", None
        ) else {}),
        # elastic multi-host summary (absent on static runs): this
        # rank's ranges run/committed and the expiries/reassignments it
        # observed — the per-rank side of the stats rank view
        **({"elastic": stats.elastic} if getattr(
            stats, "elastic", None
        ) else {}),
        # persistent-compile-cache accounting for THIS run: fresh XLA
        # compiles (misses) vs cache loads (hits) and seconds saved —
        # a warmed rerun reports misses == 0 (absent on oracle runs)
        **({"compile_cache": compile_cache} if compile_cache is not None
           else {}),
        # bucket-plan-cache traffic THIS run caused, and the shape
        # classes THIS run dispatched first — snapshot-and-diff deltas,
        # correct even deep into a multi-job serving process
        **({"plan_cache": plan_cache} if plan_cache is not None else {}),
        **({"shape_classes": shape_classes} if shape_classes is not None
           else {}),
        # reduced-precision summary (absent on f32 runs): the precision,
        # the sampled QC-cosine gate result vs the f32 oracle, and the
        # documented tolerance it cleared — see docs/performance.md
        **({"precision": stats.precision} if getattr(
            stats, "precision", None
        ) else {}),
        # which serving worker lane ran this job (absent on one-shot
        # runs): with concurrent lanes sharing one daemon, a job journal
        # must stay attributable to the lane — and backend — that ran it
        **({"worker": getattr(args, "_serve_worker")}
           if getattr(args, "_serve_worker", None) is not None else {}),
    )
    tracer = tracing.current()
    _restore_tracer(args)  # only uninstalls what this run installed
    journal.close()
    chrome = getattr(args, "chrome_trace", None)
    if chrome and tracer.enabled:
        n = tracer.write_chrome_trace(
            chrome, pid=tracing.rank_of_path(chrome)
        )
        logger.info("chrome trace (%d spans) -> %s", n, chrome)
    if getattr(args, "metrics_out", None):
        registry = getattr(backend, "metrics", None) or MetricsRegistry()
        export_run_metrics(registry, stats, device)
        registry.write_textfile(args.metrics_out)
        logger.info("metrics -> %s", args.metrics_out)


def _elastic_range_paths(args, k: int):
    """The per-range output/QC paths range ``k`` commits to.  Part files
    are numbered by RANGE, not rank — ranges are contiguous cluster
    blocks in plan order, so concatenating parts in range order
    reproduces the single-host serial bytes no matter which rank ran
    what."""
    out = f"{args.output}.part{k:05d}"
    qc = (
        f"{args.qc_report}.part{k:05d}"
        if getattr(args, "qc_report", None) else None
    )
    return out, qc


def _run_elastic_range(
    args, coord, claim, clusters, backend, scores, stats, journal,
    harness: Harness,
) -> bool:
    """Run ONE claimed chunk range through the existing checkpointed
    executor and commit it.

    The range gets its own output part, QC shard, and (coordinator-
    owned) schema-2 resume manifest; ``_checkpointed_run`` therefore
    brings the whole PR5 integrity machinery to a takeover for free — a
    dead rank's committed chunks are trusted via the manifest's sha256,
    a torn tail is truncated at the record boundary, and only the
    uncommitted remainder is recomputed, so the committed part is
    byte-identical to what any single rank would have produced."""
    from specpride_tpu.parallel.elastic import sha256_file
    from specpride_tpu.robustness.errors import LeaseExpiredError

    k = claim.range.range_id
    sub = clusters[claim.range.start : claim.range.stop]
    args_k = argparse.Namespace(**vars(args))
    args_k.output, args_k.qc_report = _elastic_range_paths(args, k)
    args_k.checkpoint = coord.checkpoint_path(k)
    args_k.append = False
    args_k._elastic_fence = lambda item: coord.commit_fence(
        k, max_idx=item.max_idx, n_clusters=len(item.part_ids),
        chunk_t0=item.chunk_t0,
    )
    args_k._elastic_clip = lambda next_min_idx: coord.clip_or_ratify(
        k, next_min_idx
    )
    qc: list | None = [] if args_k.qc_report else None
    try:
        resumed, failed, qc_failed = _checkpointed_run(
            backend, args.method, sub, args_k, stats, scores, qc=qc,
            journal=journal,
            quarantine=getattr(args, "_quarantine", None),
            harness=harness,
        )
        # a mid-run split narrowed this range: the suffix past the cut
        # belongs to the stealing rank's overlay range now, so this
        # range's QC shard and commit marker cover [start, cut) only
        rng_eff = coord.effective_range(k)
        if rng_eff.stop < claim.range.stop:
            sub = clusters[claim.range.start : rng_eff.stop]
        if qc is not None:
            _write_qc_report(
                args_k, backend, sub, qc, stats, resumed, failed,
                qc_failed,
            )
    except LeaseExpiredError as e:
        # another rank holds this range now (we stalled past the TTL,
        # or a zombie dispatch reached past a ratified cut): abandon —
        # our partial state is exactly what ITS resume pass repairs —
        # and go claim fresh work
        logger.warning(
            "rank %d abandoning range %d: %s", coord.rank, k, e,
        )
        coord.release(k)
        return False
    manifest = {}
    try:
        with open(args_k.checkpoint, encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (OSError, ValueError):
        manifest = {}
    output_bytes = manifest.get("output_bytes")
    sha = manifest.get("sha256")
    if not isinstance(output_bytes, int) or not sha:
        # an empty range writes no chunk (hence no manifest): the commit
        # marker still needs verifiable bytes for merge-parts
        output_bytes = os.path.getsize(args_k.output)
        sha = sha256_file(args_k.output, output_bytes)
    committed = coord.commit(k, {
        "start": rng_eff.start,
        "stop": rng_eff.stop,
        "part": os.path.basename(args_k.output),
        "output_bytes": output_bytes,
        "sha256": sha,
        "n_clusters": rng_eff.n_clusters,
    })
    if not committed:
        # the double-commit race: a zombie peer finished the same range
        # first.  Both parts hold identical bytes (per-cluster methods +
        # the fence), so losing the marker race is benign — exactly one
        # commit counts.
        logger.warning(
            "rank %d: range %d was already committed by another rank",
            coord.rank, k,
        )
    coord.release(k)
    return True


def _run_elastic(
    args, command: str, clusters, backend, scores, stats: RunStats,
    quarantine: Quarantine | None,
) -> None:
    """``--elastic DIR``: the dynamic replacement for the static
    ``_shard_for_process`` block partition (ROADMAP item 4).

    Every rank runs this same loop: claim a chunk range under a lease,
    run it through ``_checkpointed_run``, commit the range exactly once,
    repeat; when nothing is claimable, poll until EVERY range has a
    commit marker — a rank out of fresh work lingers as a warm spare, so
    a peer dying at any point is noticed (lease expiry) and its
    uncommitted chunks are reassigned.  Add hosts, survive losing
    them."""
    from specpride_tpu.parallel.coordinator import Coordinator

    if getattr(args, "append", False):
        raise SystemExit(
            "--append is not supported with --elastic (each range owns "
            "its part file; merge with `specpride merge-parts`)"
        )
    if getattr(args, "checkpoint", None):
        # silently ignoring the user's path would strand any script that
        # resumes/verifies against it
        raise SystemExit(
            "--checkpoint is coordinator-owned with --elastic (per-range "
            "manifests live under <DIR>/ck/ — reassignment depends on "
            "them); drop the flag"
        )
    from specpride_tpu.parallel.store import is_remote_spec

    root = args.elastic
    local_dir = None
    if is_remote_spec(root):
        # coordination state lives in the object store; the per-range
        # resume manifests stay on a filesystem (they are atomic-replace
        # checkpoint files) — shared between co-hosted ranks so a
        # takeover resumes instead of recomputing
        local_dir = (
            getattr(args, "elastic_local", None) or f"{args.output}.elastic"
        )
        os.makedirs(local_dir, exist_ok=True)
    else:
        os.makedirs(root, exist_ok=True)
    rank = getattr(args, "process_id", None)
    if rank is None:
        rank = Coordinator.assign_rank(root)
    rank = int(rank)
    # the fault plan (chaos CI's rank_kill/rank_slow) and journal names
    # key off the rank — pin it for everything built below
    args.process_id = rank
    # per-rank telemetry shards, exactly like static multi-host runs
    # (outputs/QC/checkpoints are per-RANGE instead — see
    # _elastic_range_paths)
    for attr in ("journal", "metrics_out", "chrome_trace"):
        if getattr(args, attr, None):
            setattr(args, attr, f"{getattr(args, attr)}.part{rank:05d}")
    if quarantine is not None:
        quarantine.rename(f"{quarantine.path}.part{rank:05d}")
    range_size = int(getattr(args, "elastic_range", 0) or 0)
    if range_size <= 0:
        range_size = 2 * max(int(getattr(args, "checkpoint_every", 512)), 1)
    # ONE trace for the whole elastic run: a fleet-spawned rank adopts
    # the supervisor's context (SPECPRIDE_TRACE, resolved inside
    # _open_run_journal), a late joiner adopts the trace the plan
    # creator registered in the coordinator record, and only the first
    # rank of an unsupervised run mints — so every rank's journal
    # carries the SAME trace_id and `specpride trace` merges them
    if getattr(args, "_trace_ctx", None) is None \
            and TraceContext.from_env() is None:
        plan = Coordinator.read_plan(root)
        args._trace_ctx = TraceContext.from_env(
            (plan or {}).get("trace")
        )
    journal = _open_run_journal(args, backend, command, len(clusters))
    if quarantine is not None:
        quarantine.bind(journal)
    _run_warmup(args, backend, journal)
    coord = Coordinator(
        root, rank, len(clusters), range_size,
        ttl=float(getattr(args, "elastic_ttl", 10.0) or 10.0),
        heartbeat_interval=float(
            getattr(args, "elastic_heartbeat", 0.0) or 0.0
        ),
        journal=journal,
        local_dir=local_dir,
        steal=getattr(args, "elastic_steal", "on") != "off",
        chunk_hint=max(int(getattr(args, "checkpoint_every", 512)), 1),
        trace=args._trace_ctx.to_env(),
    )
    logger.info(
        "elastic rank %d: %d ranges of <=%d clusters via %s "
        "(ttl %.1fs, steal %s)", rank, len(coord.ranges), range_size,
        coord.store.describe(), coord.ttl,
        "on" if coord.steal_enabled else "off",
    )
    autotune = getattr(args, "autotune", "off") or "off"
    ctl_thread = None
    if autotune != "off":
        if not journal.enabled:
            raise SystemExit(
                "--autotune observe|on requires --journal: every "
                "decision must be journaled as evidence"
            )
        from specpride_tpu.autotune.controller import (
            Controller,
            ControllerThread,
        )
        from specpride_tpu.autotune.policy import ElasticRangePolicy

        chunk = max(int(getattr(args, "checkpoint_every", 512)), 1)
        ctl = Controller(journal, mode=autotune)
        ctl.register(
            ElasticRangePolicy(
                lo=chunk, hi=4 * range_size, chunk_hint=chunk,
            ),
            get=lambda: coord.split_hint or range_size,
            set=coord.set_split_hint,
        )
        ctl_thread = ControllerThread(ctl, interval=1.0).start()
        logger.info(
            "elastic rank %d: autotune %s (elastic_range clamp "
            "[%d, %d])", rank, autotune, chunk, 4 * range_size,
        )
    exporter = None
    metrics_fn = None
    if getattr(args, "metrics_port", None) is not None:
        from specpride_tpu.observability.exporter import (
            ElasticTelemetry,
            MetricsExporter,
        )

        telemetry = ElasticTelemetry(
            coord,
            extra_registries=tuple(
                r for r in (getattr(backend, "metrics", None),)
                if r is not None
            ),
        )
        metrics_fn = telemetry.exposition
        exporter = MetricsExporter(
            telemetry.exposition,
            host=getattr(args, "metrics_host", "127.0.0.1"),
            port=args.metrics_port,
            health=telemetry.health,
        ).start()
        logger.info("elastic liveness metrics -> %s", exporter.url)
    flightrec = getattr(args, "flightrec", "off") or "off"
    recorder = None
    if flightrec != "off":
        if not journal.enabled:
            raise SystemExit(
                "--flightrec observe|on requires --journal: the "
                "detectors fold the journal stream"
            )
        from specpride_tpu.observability.flightrec import FlightRecorder

        ctl_ref = ctl_thread.controller if ctl_thread else None
        recorder = FlightRecorder(
            journal,
            mode=flightrec,
            incident_dir=getattr(args, "incident_dir", None),
            metrics_fn=metrics_fn,
            autotune_fn=(
                (lambda: {"status": ctl_ref.status(),
                          "knobs": ctl_ref.knob_values()})
                if ctl_ref is not None else None
            ),
            # the coordinator's lease-state counters ride every bundle
            # — the store-derived view a dead rank's journal alone
            # cannot reconstruct
            extra_fn=coord.counters,
            config={
                "host": "elastic",
                "rank": rank,
                "store": coord.store.describe(),
                "n_ranges": len(coord.ranges),
                "range_size": range_size,
                "ttl_s": coord.ttl,
                "steal": coord.steal_enabled,
                "autotune": autotune,
                "flightrec": flightrec,
            },
        ).start()
        logger.info("elastic rank %d: flightrec %s", rank, flightrec)
    # ONE harness for the whole rank lifetime: fault-plan visit counters
    # (chaos CI's rank_kill AFTER offsets) and retry accounting must
    # span ranges, not reset at every range boundary
    harness = Harness.from_args(args, journal)
    try:
        while True:
            claim = coord.claim_next()
            if claim is None:
                if coord.all_committed():
                    break
                # every open range is leased by a (presumed) live peer:
                # tier 2 — try to STEAL a split of the most-loaded live
                # peer's range before lingering as a warm spare (either
                # way a peer's death is still noticed via lease expiry)
                claim = coord.try_steal()
            if claim is None:
                coord.wait_for_work()
                continue
            _run_elastic_range(
                args, coord, claim, clusters, backend, scores, stats,
                journal, harness,
            )
    finally:
        harness.close()
        if ctl_thread is not None:
            # final progress beat first: a rank that finished inside
            # one heartbeat interval would hand the drain tick a
            # journal with no chunk walls to decide on.  Then stop the
            # controller before coord.stop(): a tick racing the
            # journal close would lose its decision line
            coord.flush_progress()
            ctl_thread.stop()
        if recorder is not None:
            # drains queued firings into the journal BEFORE
            # _finish_run closes it — a drained rank keeps its evidence
            recorder.stop()
        if exporter is not None:
            exporter.stop()
        coord.stop()
    _save_shape_manifest(args, backend)
    stats.elastic = {
        "rank": rank,
        "backend": coord.store.describe(),
        "n_ranges": len(coord.ranges),
        "range_size": range_size,
        **coord.counters(),
    }
    _finish_run(args, backend, stats, journal)


def _run_pipeline_command(args, command: str, backend=None) -> dict:
    """THE consensus/select execution body — the one copy both one-shot
    CLI commands and the serving daemon's job runner execute, so a
    served job can never drift behaviorally from its CLI equivalent
    (that parity is what tests/test_serve.py and the ci.sh serve pass
    byte-compare).

    ``backend``: an already-constructed resident backend (the daemon's
    warm one, jit caches and seen-shape manifest intact) or None to
    construct per run from the args as the CLI always did.  Returns the
    run's stats summary (the CLI prints it on stderr; the daemon ships
    it in the job's terminal response)."""
    stats = RunStats()
    if command == "consensus" and args.method == "bin-mean":
        try:
            _bin_mean_config(args)
        except ValueError as e:
            raise SystemExit(f"invalid bin-mean options: {e}")
    _install_tracer_early(args)
    quarantine = (
        Quarantine(args.output + ".quarantine.mgf")
        if getattr(args, "on_error", "abort") == "skip" else None
    )
    args._quarantine = quarantine  # _shard_for_process renames per rank
    journal = None
    try:
        if _is_mzml(args.input):
            clusters = _clusters_from_mzml(args.input, args, stats)
        else:
            clusters = _load_clusters_served(args, stats, quarantine)
        if command == "consensus" and args.single:
            # whole file = one cluster; the reference titles the result
            # with the output filename (ref
            # average_spectrum_clustering.py:203-205).  Zero input spectra
            # stay zero clusters — a truly empty cluster would crash the
            # backends.
            spectra = [s for c in clusters for s in c.members]
            clusters = [Cluster(args.output, spectra)] if spectra else []
        if backend is None:
            backend = _get_backend(args)
        from specpride_tpu.cache import result_cache as _result_cache

        # the content-addressed result cache: per-run context over the
        # tiers named by --result-cache/--result-store, or the serving
        # daemon's boot-owned singleton; None when the cache is off or
        # this run is ineligible (non-cacheable method, batch member)
        args._result_cache = _result_cache.runtime_for(
            args, command, backend=backend
        )
        scores = (
            _load_scores(args)
            if command == "select" and args.method == "best" else None
        )
        clusters, args.output = _shard_for_process(clusters, args)
        if getattr(args, "metrics_port", None) is not None and not (
            getattr(args, "elastic", None)
        ):
            logger.warning(
                "--metrics-port only serves the elastic rank-liveness "
                "exporter; ignoring it without --elastic (end-of-run "
                "metrics: --metrics-out)"
            )
        if getattr(args, "elastic", None):
            # dynamic chunk-range distribution with rank-fault tolerance
            # replaces the single checkpointed run below; _run_elastic
            # owns its (per-rank) journal and run_end.  The precision
            # gate runs FIRST — before this rank claims any range — so
            # a reduced-precision configuration that cannot demonstrate
            # fidelity on this data aborts before computing anything
            # (the verdict rides stats.precision into each range's
            # run_end; the per-rank journal is not open yet, so the
            # standalone gate event goes unjournaled here)
            _precision_gate(
                args, backend, clusters, args.method, stats,
                NullJournal(),
            )
            _run_elastic(
                args, command, clusters, backend, scores, stats,
                quarantine,
            )
            return stats.summary()
        journal = _open_run_journal(args, backend, command, len(clusters))
        if quarantine is not None:
            quarantine.bind(journal)  # flush blocks found during parse
        _run_warmup(args, backend, journal)
        qc = [] if getattr(args, "qc_report", None) else None
        with device_trace(getattr(args, "trace_dir", None)):
            resumed, failed, qc_failed = _checkpointed_run(
                backend, args.method, clusters, args, stats, scores, qc=qc,
                journal=journal, quarantine=quarantine,
            )
        if qc is not None:
            _write_qc_report(args, backend, clusters, qc, stats, resumed,
                             failed, qc_failed)
        # reduced-precision runs must demonstrate fidelity on their own
        # data before the run may succeed (journals run_end.precision;
        # a breach aborts here, after the QC report, so the evidence an
        # operator needs to diagnose it is already on disk)
        _precision_gate(args, backend, clusters, args.method, stats,
                        journal)
        _save_shape_manifest(args, backend)
        if command == "consensus":
            logger.info(
                "consensus done: %.1f clusters/sec",
                stats.throughput("clusters"),
            )
        _finish_run(args, backend, stats, journal)
    finally:
        if quarantine is not None:
            quarantine.close()
        _restore_tracer(args)  # no-op after a clean _finish_run
        if getattr(args, "_serve_worker", None) is not None:
            # a served job that aborted before _finish_run must not
            # leave its plan-cache scope on the worker thread, where the
            # NEXT job's pack traffic would land in it (idempotent
            # after a clean _finish_run)
            from specpride_tpu.data.packed import set_plan_scope

            set_plan_scope(None)
        if journal is not None:
            # a failed run must not leak the journal fd: the one-shot
            # CLI's process exit used to hide this, a serving daemon
            # running thousands of jobs does not (close() after
            # _finish_run's own close is a guarded no-op)
            journal.close()
    return stats.summary()


def cmd_consensus(args) -> int:
    print(json.dumps(_run_pipeline_command(args, "consensus")),
          file=sys.stderr)
    return 0


def cmd_select(args) -> int:
    print(json.dumps(_run_pipeline_command(args, "select")),
          file=sys.stderr)
    return 0


def cmd_warmup(args) -> int:
    """``specpride warmup MANIFEST``: AOT-compile every kernel variant a
    shape manifest records, concurrently, populating the persistent
    compilation cache — so the NEXT run (or the first request a serving
    daemon takes) performs zero fresh XLA compiles.  Per-kernel
    compile-vs-cache-hit and seconds are journaled as warmup events."""
    import time as _time

    from specpride_tpu.observability import device_summary
    from specpride_tpu.warmstart import cache as ws_cache
    from specpride_tpu.warmstart.manifest import load_manifest
    from specpride_tpu.warmstart.warmup import warm_entries

    ws_cache.configure_compile_cache(getattr(args, "compile_cache", None))
    try:
        entries = load_manifest(args.manifest)
    except (OSError, ValueError) as e:
        raise SystemExit(f"unreadable shape manifest {args.manifest}: {e}")
    journal = open_journal(getattr(args, "journal", None))
    state = ws_cache.cache_state()
    journal.emit(
        "run_start", command="warmup", method="warmup", backend="tpu",
        n_clusters=0, manifest=args.manifest,
    )
    journal.emit(
        "compile_cache", enabled=state.enabled, dir=state.dir,
        reason=state.reason, source=state.source,
    )
    snapshot = ws_cache.counters_snapshot()
    t0 = _time.perf_counter()
    from specpride_tpu.backends.tpu_backend import _cpu_only_devices

    results = warm_entries(
        entries, journal=journal, jobs=args.jobs,
        # match the twin a run on this host will dispatch: donation
        # resolves off on cpu-only hosts, off with --no-donate
        donate=(
            not getattr(args, "no_donate", False)
            and not _cpu_only_devices()
        ),
    )
    elapsed = _time.perf_counter() - t0
    n_hits = sum(r.cache_hit for r in results)
    n_compiled = sum(r.status == "compiled" for r in results)
    for r in results:
        if r.status == "error":
            logger.warning(
                "warmup %s %s failed: %s", r.entry.kernel,
                list(r.entry.shape_key), r.detail,
            )
    journal.emit(
        "run_end",
        counters={
            "kernels_warmed": len(results),
            "warmup_cache_hits": n_hits,
            "warmup_compiled": n_compiled,
        },
        phases_s={"warmup": round(elapsed, 4)},
        elapsed_s=round(elapsed, 4),
        device=device_summary(None),
        compile_cache=ws_cache.counters_delta(snapshot),
    )
    journal.close()
    print(json.dumps({
        "kernels": len(results),
        "compiled": n_compiled,
        "cache_hits": n_hits,
        "skipped_or_failed": len(results) - n_hits - n_compiled,
        "seconds": round(elapsed, 3),
        "cache_dir": state.dir,
    }))
    return 0


def cmd_serve(args) -> int:
    """``specpride serve``: boot the warm-kernel consensus daemon and
    serve consensus/select jobs over a local socket until SIGTERM
    (graceful drain).  See docs/serving.md."""
    from specpride_tpu.observability.exporter import parse_slo_spec
    from specpride_tpu.serve.daemon import ServeDaemon
    from specpride_tpu.serve.scheduler import parse_quota_spec

    try:
        slo = parse_slo_spec(args.slo)
        quotas = parse_quota_spec(args.quota)
    except ValueError as e:
        raise SystemExit(str(e))
    if args.workers < 0:
        raise SystemExit(f"--workers must be >= 0 (got {args.workers})")
    if args.batch_window < 0:
        raise SystemExit(
            f"--batch-window must be >= 0 ms (got {args.batch_window})"
        )
    if args.batch_max_clusters < 1:
        raise SystemExit(
            "--batch-max-clusters must be >= 1 "
            f"(got {args.batch_max_clusters})"
        )
    autotune = getattr(args, "autotune", "off") or "off"
    if autotune != "off" and not args.journal:
        raise SystemExit(
            "serve --autotune observe|on requires --journal: every "
            "decision must be journaled as evidence"
        )
    flightrec = getattr(args, "flightrec", "off") or "off"
    if flightrec != "off" and not args.journal:
        raise SystemExit(
            "serve --flightrec observe|on requires --journal: the "
            "detectors fold the journal stream"
        )
    if flightrec == "on" and not getattr(args, "incident_dir", None):
        raise SystemExit(
            "serve --flightrec on dumps bundles and therefore "
            "requires --incident-dir (use 'observe' to journal "
            "firings without bundles)"
        )
    if getattr(args, "result_store", None) and not \
            getattr(args, "result_cache", None):
        raise SystemExit(
            "serve --result-store is the SHARED tier of the result "
            "cache; it requires --result-cache DIR[:MB] for the local "
            "tier"
        )
    autotune_bw = None
    if getattr(args, "autotune_batch_window", None):
        from specpride_tpu.autotune.policy import parse_clamp

        try:
            autotune_bw = parse_clamp(
                args.autotune_batch_window, "--autotune-batch-window"
            )
        except ValueError as e:
            raise SystemExit(str(e))
    return ServeDaemon(
        args.socket,
        max_queue=args.max_queue,
        workers=args.workers,
        batch_window=args.batch_window / 1000.0,
        batch_max_clusters=args.batch_max_clusters,
        quotas=quotas,
        compile_cache=args.compile_cache,
        routing_table=args.routing_table,
        layout=args.layout,
        force_device=args.force_device,
        precision=getattr(args, "precision", "f32") or "f32",
        donate=not getattr(args, "no_donate", False),
        warmup=args.warmup,
        warmup_manifest=args.warmup_manifest,
        warmup_jobs=args.warmup_jobs,
        watchdog_timeout=args.watchdog_timeout,
        journal_path=args.journal,
        journal_rotate_mb=args.journal_rotate_mb,
        metrics_port=args.metrics_port,
        metrics_host=args.metrics_host,
        metrics_out=args.metrics_out,
        slo=slo,
        autotune=autotune,
        autotune_interval=getattr(args, "autotune_interval", 1.0),
        autotune_batch_window=autotune_bw,
        flightrec=flightrec,
        incident_dir=getattr(args, "incident_dir", None),
        result_cache=getattr(args, "result_cache", None),
        result_store=getattr(args, "result_store", None),
    ).run()


def cmd_profile(args) -> int:
    """``specpride profile``: capture a bounded ``jax.profiler`` device
    trace (plus the daemon-journal window) on a RUNNING warm daemon —
    no restart, no cold recompile on the next job.  Prints the reply
    JSON (artifact paths) on stdout; exit 0 captured, 75 retriable
    (another capture in flight — retry later), 2 rejected, 1 error."""
    from specpride_tpu.serve import client as serve_client
    from specpride_tpu.serve import protocol as serve_protocol

    try:
        msg = serve_client.profile(
            args.socket, seconds=args.seconds,
            trace_dir=args.trace_dir, chrome_trace=args.chrome_trace,
            timeout=args.timeout,
        )
    except (OSError, serve_client.ServeError) as e:
        print(
            json.dumps({
                "ok": False, "status": "error",
                "error": f"{type(e).__name__}: {e}", "retriable": True,
            }),
            flush=True,
        )
        return serve_protocol.EX_TEMPFAIL
    print(json.dumps(msg), flush=True)
    if msg.get("status") == "profiled":
        return 0
    if msg.get("retriable"):
        return serve_protocol.EX_TEMPFAIL
    return 2 if msg.get("status") == "rejected" else 1


def cmd_submit(args) -> int:
    """``specpride submit -- consensus IN OUT ...``: run one job through
    a serving daemon.  Streams the daemon's status lines as JSON on
    stdout; exit code 0 = done, 75 = retriable rejection (queue full /
    quota / draining — resubmit after backoff), 2 = permanently
    rejected, 1 = job error.

    ``--retry N`` folds the resubmit loop preempted-fleet tenants
    otherwise hand-roll into the client: a retriable (exit-75 class)
    outcome is retried up to N times with the robustness layer's
    exponential backoff + deterministic jitter; permanent outcomes
    never retry.  Resubmitting is safe because served jobs are
    idempotent (same argv -> same bytes)."""
    import time as _time

    from specpride_tpu.robustness.retry import RetryPolicy
    from specpride_tpu.serve import client as serve_client
    from specpride_tpu.serve import protocol as serve_protocol

    job = list(args.job)
    if job and job[0] == "--":
        job = job[1:]
    if not job:
        raise SystemExit(
            "submit needs a job argv after --, e.g.: "
            "specpride submit -- consensus in.mgf out.mgf --method bin-mean"
        )
    retries = max(int(getattr(args, "retry", 0) or 0), 0)
    policy = RetryPolicy(
        retries=retries, backoff=getattr(args, "retry_backoff", 0.5),
    )
    # ONE trace across every resubmit attempt: the retries are hops of
    # the same logical request, and the client journal (--journal)
    # shows them as sibling submit spans under one trace_id
    ctx = TraceContext.from_env() or TraceContext.mint()

    def _attempt() -> int:
        last = None
        try:
            for msg in serve_client.submit(args.socket, job,
                                           timeout=args.timeout,
                                           client=args.client,
                                           journal=args.journal,
                                           trace=ctx):
                print(json.dumps(msg), flush=True)
                last = msg
        except (OSError, serve_client.ServeError) as e:
            print(
                json.dumps({
                    "ok": False, "status": "error",
                    "error": f"{type(e).__name__}: {e}", "retriable": True,
                }),
                flush=True,
            )
            return 75
        return serve_client.exit_code(last)

    attempt = 0
    while True:
        rc = _attempt()
        if rc != serve_protocol.EX_TEMPFAIL or attempt >= retries:
            return rc
        wait = policy.backoff_s("submit", attempt)
        print(
            json.dumps({
                "status": "retrying", "attempt": attempt + 1,
                "of": retries, "backoff_s": round(wait, 3),
            }),
            flush=True,
        )
        _time.sleep(wait)
        attempt += 1


def cmd_fleet(args) -> int:
    """``specpride fleet --ranks N --spares M -- consensus … --elastic
    SPEC``: the warm-spare autoscaling supervisor.  Spawns N rank
    processes over the supervised argv, replaces abnormal exits while
    work remains, scales up to M spares on stale heartbeats or a long
    completion horizon, retires idle excess — every decision journaled
    as rank_spawn/rank_retire.  Exits 0 once every range is committed
    (merge with `specpride merge-parts`)."""
    from specpride_tpu.observability.journal import open_journal
    from specpride_tpu.parallel.fleet import FleetSupervisor

    job = list(args.job)
    if job and job[0] == "--":
        job = job[1:]
    if not job:
        raise SystemExit(
            "fleet needs a supervised argv after --, e.g.: specpride "
            "fleet --ranks 2 -- consensus in.mgf out.mgf --method "
            "bin-mean --elastic /shared/coord"
        )
    # ONE trace for the whole fleet: the supervisor mints (or inherits)
    # the context and hands it to every spawned rank via the
    # SPECPRIDE_TRACE env, so all rank journals + the fleet journal
    # carry the same trace_id and merge onto one causal timeline
    ctx = TraceContext.from_env() or TraceContext.mint()
    env = dict(os.environ)
    env[tracing.TRACE_ENV] = ctx.to_env()
    journal = open_journal(args.journal)
    journal.bind_trace(ctx.trace_id)
    if journal.enabled:
        emit_clock_anchor(journal)
    try:
        try:
            sup = FleetSupervisor(
                job, ranks=args.ranks, spares=args.spares,
                max_ranks=args.max_ranks, journal=journal,
                poll_interval=args.poll,
                scale_horizon=args.scale_horizon,
                env=env,
                autotune=getattr(args, "autotune", "off") or "off",
                flightrec=getattr(args, "flightrec", "off") or "off",
                incident_dir=getattr(args, "incident_dir", None),
            )
        except ValueError as e:
            raise SystemExit(str(e))
        rc = sup.run(timeout=args.timeout)
        summary = sup.summary()
        logger.info(
            "fleet done: %d spawned, %d retired, %d replaced",
            summary["spawned"], summary["retired"], summary["replaced"],
        )
        print(json.dumps(summary), file=sys.stderr)
        if rc != 0:
            for problem in summary["failures"]:
                logger.error("fleet: %s", problem)
        return rc
    finally:
        journal.close()


def cmd_cas_server(args) -> int:
    """``specpride cas-server``: the in-tree conditional-put/ETag object
    store — the reference backend behind ``--elastic URL``, used by CI
    and the bench so the object-store protocol is exercised without a
    cloud account.  Prints its URL on stdout (and to --url-file for
    scripts) and serves until SIGTERM/SIGINT."""
    from specpride_tpu.parallel.store import CasServer

    server = CasServer(host=args.host, port=args.port)
    print(server.url, flush=True)
    if args.url_file:
        with open(args.url_file, "w", encoding="utf-8") as fh:
            fh.write(server.url + "\n")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        try:
            server.stop()
        except Exception:  # noqa: BLE001 - already shutting down
            pass
    return 0


def cmd_stats(args) -> int:
    from specpride_tpu.observability.stats_cli import follow_stats, run_stats

    if getattr(args, "trace", None):
        # the critical-path view of ONE causal trace across the given
        # shards: which hop (client wait, daemon queue, batch, kernel)
        # to shorten first
        from specpride_tpu.observability import traceplane

        view = traceplane.extract_trace(args.journals, args.trace)
        for w in view.warnings:
            print(f"warning: {w}", file=sys.stderr)
        traceplane.render_critical_path(view, sys.stdout)
        return 0 if view.spans else 1
    if getattr(args, "follow", False):
        if len(args.journals) != 1:
            raise SystemExit("--follow tails exactly one journal")
        return follow_stats(
            args.journals[0], interval=args.interval,
            top_spans=args.top_spans, slo=args.slo,
            autotune=getattr(args, "autotune", False),
            incidents=getattr(args, "incidents", False),
        )
    return run_stats(
        args.journals, json_out=args.json, top_spans=args.top_spans,
        slo=args.slo, autotune=getattr(args, "autotune", False),
        incidents=getattr(args, "incidents", False),
    )


def cmd_autotune_replay(args) -> int:
    """``specpride autotune-replay JOURNAL``: the controller's
    determinism audit — rebuild each recorded policy from its journaled
    params, re-run it on the journaled signal snapshot, refold the
    snapshots from the event stream, and require everything to match.
    Exit 0 iff every decision reproduces.  See docs/autotune.md."""
    from specpride_tpu.autotune.replay import render_replay, replay_journal

    result = replay_journal(args.journal)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(result, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
    render_replay(result, sys.stdout)
    return 0 if result["ok"] else 1


def cmd_incident_replay(args) -> int:
    """``specpride incident-replay JOURNAL``: the flight recorder's
    determinism audit — refold the journal stream through the detector
    set and require every recorded ``incident`` event (id, reason,
    clock, evidence, trace id, dedup suppression) to re-derive
    bit-exact.  Exit 0 iff everything reproduces.  See
    docs/observability.md."""
    from specpride_tpu.observability.flightrec import (
        render_incident_replay,
        replay_incidents,
    )

    result = replay_incidents(args.journal)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(result, fh, indent=2, sort_keys=True, default=str)
            fh.write("\n")
    render_incident_replay(result, sys.stdout)
    return 0 if result["ok"] else 1


def cmd_incidents(args) -> int:
    """``specpride incidents list|show|export``: read the atomic
    bundles a ``--flightrec on`` host dumped under its
    ``--incident-dir``.  ``list`` is one line per bundle; ``show``
    prints one bundle's manifest (+ its evidence files with
    ``--files``); ``export`` tars one bundle (or all of them) for
    attaching to a report."""
    from specpride_tpu.observability.flightrec import (
        find_bundle,
        list_bundles,
    )

    bundles, warnings = list_bundles(args.incident_dir)
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    if args.action == "list":
        if not bundles:
            print(f"no incident bundles under {args.incident_dir}")
            return 0
        for b in bundles:
            inc = b.get("incident", {})
            print(
                f"{inc.get('incident_id', '?'):<18} "
                f"{inc.get('detector', '?'):<16} "
                f"clock={inc.get('clock', '?')} "
                f"mode={inc.get('mode', '?')} "
                f"suppressed={inc.get('suppressed', 0)}  "
                f"{inc.get('reason', '')}"
            )
        return 0
    if not args.incident_id:
        raise SystemExit(f"incidents {args.action} needs an INCIDENT_ID")
    bundle = find_bundle(args.incident_dir, args.incident_id)
    if bundle is None:
        raise SystemExit(
            f"no unique bundle matches {args.incident_id!r} under "
            f"{args.incident_dir} (try `specpride incidents list`)"
        )
    if args.action == "show":
        print(json.dumps(bundle, indent=2, sort_keys=True, default=str))
        if args.files:
            for fname in sorted(
                f for f in os.listdir(bundle["dir"])
                if f != "manifest.json"
            ):
                path = os.path.join(bundle["dir"], fname)
                print(f"\n===== {fname} =====")
                with open(path, encoding="utf-8",
                          errors="replace") as fh:
                    sys.stdout.write(fh.read())
        return 0
    # export: one deterministic tarball of the bundle directory
    import tarfile

    inc = bundle.get("incident", {})
    out = args.output or (
        f"incident-{inc.get('incident_id', 'unknown')}.tar.gz"
    )
    with tarfile.open(out, "w:gz") as tar:
        tar.add(bundle["dir"], arcname=os.path.basename(bundle["dir"]))
    print(out)
    return 0


def cmd_trace(args) -> int:
    """Reconstruct a Chrome trace from one or more run journals, merging
    multi-host ``.part<rank>`` shards onto a single timeline (pid = rank).
    A post-mortem tool: schema violations (e.g. the torn final line of a
    killed run) are reported on stderr and dropped, never fatal.

    ``--trace-id ID`` (or ``--job JOBID``, resolved through the serving
    events) switches to the CAUSAL mode: extract exactly one trace's
    spans from all the shards, align every process's monotonic timeline
    onto one wall axis via the journaled clock anchors (bounded skew),
    and emit flow arrows across process tracks — client submit ->
    daemon queue/job -> shared batch -> job pipeline / elastic ranks on
    ONE timeline."""
    from specpride_tpu.observability.tracing import build_chrome_trace

    if getattr(args, "job", None) is not None or getattr(
        args, "trace_id", None
    ):
        return _cmd_trace_causal(args)
    n_spans, n_files, warnings, violations = build_chrome_trace(
        args.journals, args.out
    )
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    for v in violations:
        print(f"dropped: {v}", file=sys.stderr)
    if n_files == 0:
        # nothing readable at all: no journal and no shards
        print("no journal files to read", file=sys.stderr)
        return 1
    if n_spans == 0 and violations:
        # every span line was invalid — almost always the wrong input
        # (e.g. chrome-trace .part files instead of the journal shards)
        print(
            "no valid span events read — pass the --journal files, not "
            "the --chrome-trace output", file=sys.stderr,
        )
        return 1
    print(f"{n_spans} spans -> {args.out}", file=sys.stderr)
    return 0


def _cmd_trace_causal(args) -> int:
    """``specpride trace --job JOBID | --trace-id ID``: one causal
    timeline across journal shards (see cmd_trace)."""
    from specpride_tpu.observability import traceplane
    from specpride_tpu.observability.journal import expand_parts

    trace_id = getattr(args, "trace_id", None)
    if trace_id is None:
        files: list[str] = []
        for p in args.journals:
            got, warn = expand_parts(p)
            files.extend(got)
            for w in warn:
                print(f"warning: {w}", file=sys.stderr)
        trace_id = traceplane.resolve_job_trace(files, args.job)
        if trace_id is None:
            print(
                f"no trace_id found for job {args.job} in the given "
                "journals (is the daemon journal among them, and does "
                "it predate schema v4?)", file=sys.stderr,
            )
            return 1
    view = traceplane.build_trace_chrome(
        args.journals, trace_id, args.out
    )
    for w in view.warnings:
        print(f"warning: {w}", file=sys.stderr)
    for v in view.violations:
        print(f"dropped: {v}", file=sys.stderr)
    if not view.spans and not view.instants:
        print(
            f"trace {trace_id}: no matching events in the given "
            "journals", file=sys.stderr,
        )
        return 1
    print(
        f"trace {trace_id}: {len(view.spans)} spans across "
        f"{len(view.shards)} process track(s), clock-skew bound "
        f"{view.skew_bound_s:.4f}s -> {args.out}", file=sys.stderr,
    )
    return 0


def cmd_merge_parts(args) -> int:
    """Concatenate multi-host ``<output>.part<id>`` shards (block-sharded
    — static rank blocks or elastic chunk ranges, part order == cluster
    order either way) into the final file.

    Refuses, naming the rank, on:

    * a **gap or duplicate** in the id sequence (expected count from
      ``--elastic``'s plan, else ``--num-processes``, else the highest
      id seen — so a missing MIDDLE shard never merges silently even
      with no flags; only a missing TAIL needs the count pinned);
    * a shard that fails its schema-2 manifest check — ``--elastic DIR``
      verifies every part's size + sha256 against its range commit
      marker, ``--checkpoint BASE`` against ``<BASE>.part<id>`` resume
      manifests from a static run.

    ``--qc-report FILE`` additionally merges the per-shard QC reports
    into FILE, byte-identical to a single-host serial run's report."""
    import glob
    import shutil

    parts = sorted(glob.glob(glob.escape(args.output) + ".part*"))
    if not parts:
        print(f"no part files match {args.output}.part*", file=sys.stderr)
        return 1
    ranks = []
    for p in parts:
        suffix = p.rsplit(".part", 1)[1]
        if not suffix.isdigit():
            print(f"unrecognized part name {p}", file=sys.stderr)
            return 1
        ranks.append(int(suffix))
    table = None
    elastic = getattr(args, "elastic", None)
    if elastic:
        from specpride_tpu.parallel.elastic import elastic_range_table

        table, problem = elastic_range_table(elastic)
        if table is None:
            print(
                f"--elastic {elastic}: {problem} — is this the "
                "coordinator store the ranks ran against?",
                file=sys.stderr,
            )
            return 1
    if table is not None:
        # elastic: the EFFECTIVE range set (base plan + work-stealing
        # overlays, cuts applied), not a dense id sequence — overlay
        # ids sit past the base plan, and cluster order is START order
        expected_ids = [row["range_id"] for row in table]
        missing = sorted(set(expected_ids) - set(ranks))
        extra = sorted(set(ranks) - set(expected_ids))
        by_id = dict(zip(ranks, parts))
        ordered = [by_id[i] for i in expected_ids if i in by_id]
        verify_order = [
            (row["range_id"], by_id[row["range_id"]])
            for row in table if row["range_id"] in by_id
        ]
    else:
        expected = args.num_processes or (max(ranks) + 1 if ranks else 0)
        missing = sorted(set(range(expected)) - set(ranks))
        extra = sorted(set(ranks) - set(range(expected)))
        ordered = [p for _, p in sorted(zip(ranks, parts))]
        verify_order = sorted(zip(ranks, parts))
    if missing or extra or len(ranks) != len(set(ranks)):
        print(
            f"incomplete part set for {args.output}: have ids {ranks}, "
            f"missing {missing}"
            + (f", unexpected {extra}" if extra else "")
            + " — refusing to merge a gapped sequence (a missing id "
            "means a rank/range never committed; pass --num-processes "
            "or --elastic to pin the expected count)",
            file=sys.stderr,
        )
        return 1
    # manifest verification BEFORE any byte moves: a corrupt or torn
    # shard must fail the merge loudly, never reach the merged output
    if table is not None or getattr(args, "checkpoint", None):
        from specpride_tpu.parallel.elastic import (
            read_done_marker,
            verify_part_manifest,
        )

        for rank, part in verify_order:
            if table is not None:
                manifest = read_done_marker(elastic, rank)
                kind = "commit marker"
                if manifest is None:
                    print(
                        f"rank/range {rank}: unreadable {kind} for range "
                        f"{rank} — refusing to merge an unverifiable "
                        "shard", file=sys.stderr,
                    )
                    return 1
            else:
                mpath = f"{args.checkpoint}.part{part.rsplit('.part', 1)[1]}"
                kind = "checkpoint manifest"
                try:
                    with open(mpath, encoding="utf-8") as fh:
                        manifest = json.load(fh)
                except (OSError, ValueError) as e:
                    print(
                        f"rank/range {rank}: unreadable {kind} {mpath} "
                        f"({e}) — refusing to merge an unverifiable "
                        "shard", file=sys.stderr,
                    )
                    return 1
            problem = verify_part_manifest(part, manifest)
            if problem is not None:
                print(
                    f"rank/range {rank}: {part} fails its {kind}: "
                    f"{problem} — refusing to merge",
                    file=sys.stderr,
                )
                return 1
    if getattr(args, "qc_report", None):
        from specpride_tpu.parallel.elastic import merge_qc_reports

        shards = []
        for rank, part in verify_order if table is not None else sorted(
            zip(ranks, parts)
        ):
            qpath = f"{args.qc_report}.part{part.rsplit('.part', 1)[1]}"
            if not os.path.exists(qpath):
                print(
                    f"rank/range {rank}: no QC shard {qpath} — refusing "
                    "a partial QC merge", file=sys.stderr,
                )
                return 1
            shards.append(qpath)
        n_rows = merge_qc_reports(shards, args.qc_report)
        logger.info(
            "merged %d QC shards (%d clusters) -> %s",
            len(shards), n_rows, args.qc_report,
        )
    with open(args.output, "wb") as out:
        # order by parsed rank, not lexically: hand-renamed mixed-width
        # names (part2 vs part00010) would otherwise merge out of order
        for p in ordered:
            with open(p, "rb") as fh:
                shutil.copyfileobj(fh, out)  # streams: parts can be huge
    if args.remove_parts:
        for p in parts:
            os.remove(p)
    logger.info("merged %d parts -> %s", len(parts), args.output)
    return 0


def cmd_lint(args) -> int:
    """Static analysis over the project tree (`specpride lint`).  The
    analyzer is pure stdlib AST work — imported lazily so the compute
    CLI never pays for it."""
    from specpride_tpu.analysis import runner as lint_runner

    return lint_runner.main(args)


def cmd_convert(args) -> int:
    from specpride_tpu import convert

    stats = RunStats()
    src = args.input
    with stats.phase("convert"):
        if src.endswith((".mzml", ".mzML", ".mzml.gz", ".mzML.gz")):
            n = convert.convert_mzml(
                src, args.msms, args.clusters, args.output, args.raw_name,
                BestSpectrumConfig(px_accession=args.px_accession),
            )
        else:
            n = convert.convert_mgf(
                src, args.msms, args.clusters, args.output,
                args.raw_name or os.path.basename(src).rsplit(".", 1)[0],
                BestSpectrumConfig(px_accession=args.px_accession),
            )
    stats.count("spectra_out", n)
    print(json.dumps(stats.summary()), file=sys.stderr)
    return 0


def cmd_evaluate(args) -> int:
    from specpride_tpu import metrics

    stats = RunStats()
    reps = {s.cluster_id: s for s in read_mgf(args.representatives)}
    clusters = _load_clusters(args.clustered, stats)
    pairs = [(reps[c.cluster_id], c) for c in clusters if c.cluster_id in reps]
    stats.count("clusters_missing_rep", len(clusters) - len(pairs))
    with device_trace(getattr(args, "trace_dir", None)), \
            stats.phase("evaluate"):
        results = metrics.evaluate(
            [p[0] for p in pairs],
            [p[1] for p in pairs],
            # a constructed backend so --mesh/--layout/--coordinator apply
            backend=(
                "numpy" if args.backend == "numpy" else _get_backend(args)
            ),
            cosine_config=CosineConfig(
                normalization=getattr(args, "normalization", "none")
            ),
        )
    summary = metrics.summarize(results)
    if args.report:
        metrics.write_report(results, args.report, args.format)
    print(json.dumps(summary))
    return 0


def cmd_plot(args) -> int:
    from specpride_tpu import viz
    from specpride_tpu.data.peaks import peptide_from_usi

    if _is_mzml(args.clustered):
        cluster_list = _clusters_from_mzml(args.clustered, args, RunStats())
    else:
        cluster_list = group_into_clusters(read_mgf(args.clustered))
    clusters = {c.cluster_id: c for c in cluster_list}
    if args.cluster_id not in clusters:
        print(f"cluster {args.cluster_id!r} not found", file=sys.stderr)
        return 1
    cluster = clusters[args.cluster_id]
    if args.consensus:
        reps = {s.cluster_id: s for s in read_mgf(args.consensus)}
        paths = viz.plot_cluster_vs_consensus(
            cluster.members, reps[args.cluster_id], args.out_prefix
        )
    else:
        peptide = args.peptide
        charge = cluster.members[0].precursor_charge
        if not peptide:
            for s in cluster.members:
                pep, z = peptide_from_usi(s.usi)
                if pep:
                    peptide, charge = pep, z or charge
                    break
        if not peptide:
            print("no peptide known for cluster; pass --peptide", file=sys.stderr)
            return 1
        paths = viz.plot_cluster_vs_theoretical(
            cluster.members, peptide, charge, args.out_prefix
        )
    print("\n".join(paths))
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="specpride",
        description="TPU-native representative-spectrum framework",
    )
    ap.add_argument("-v", "--verbose", action="count", default=0)
    ap.add_argument("--log-json", action="store_true",
                    help="structured JSON logs on stderr")
    sub = ap.add_subparsers(dest="command", required=True)

    pc = sub.add_parser("consensus", help="merge clusters into consensus spectra")
    pc.add_argument("input")
    pc.add_argument("output")
    pc.add_argument("--method", choices=["bin-mean", "gap-average"],
                    default="bin-mean")
    _add_backend(pc)
    pc.add_argument("--min-mz", type=float, default=100.0)
    pc.add_argument("--max-mz", type=float, default=2000.0)
    pc.add_argument("--bin-size", type=float, default=0.02)
    pc.add_argument("--no-quorum", action="store_true")
    pc.add_argument("--quorum-fraction", type=float, default=0.25)
    pc.add_argument(
        "--tolerance-mode", choices=["da", "ppm"], default="da",
        help="bin-mean grid: fixed-Da bins (reference) or "
        "mass-proportional ppm bins",
    )
    pc.add_argument("--ppm", type=float, default=20.0,
                    help="bin width in ppm for --tolerance-mode ppm")
    pc.add_argument(
        "--qc-normalization", choices=["none", "sqrt", "log"],
        default="none",
        help="intensity transform for the QC cosine (sqrt tempers "
        "dominant peaks; log flattens dynamic range)",
    )
    pc.add_argument("--mz-accuracy", type=float, default=0.01)
    pc.add_argument("--dyn-range", type=float, default=1000.0)
    pc.add_argument("--min-fraction", type=float, default=0.5)
    pc.add_argument("--tail-mode", choices=["reference", "split"],
                    default="reference")
    pc.add_argument("--pepmass", choices=["naive_average", "neutral_average",
                                          "lower_median"],
                    default="lower_median")
    pc.add_argument("--rt", choices=["median", "mass_lower_median"],
                    default="median")
    pc.add_argument("--single", action="store_true",
                    help="treat the whole input file as one cluster "
                         "(ref average_spectrum_clustering.py:172-176)")
    _add_execution(pc)
    pc.add_argument(
        "--qc-report", metavar="FILE",
        help="also compute each representative's mean member cosine in the "
        "same pass (bin-mean: fused with the consensus dispatch) and write "
        "the per-cluster QC report here",
    )
    pc.add_argument(
        "--clusters",
        help="MaRaCluster TSV — consume a raw .mzML input directly, no "
        "convert step (ref binning.py:33-118)",
    )
    pc.add_argument("--msms", help="MaxQuant msms.txt for peptide titles "
                                   "(direct .mzML input; optional)")
    pc.add_argument("--raw-name", help="raw file name for USIs "
                                       "(direct .mzML input)")
    pc.add_argument("--px-accession", default="PXD004732")
    _add_observability(pc)
    pc.set_defaults(fn=cmd_consensus)

    ps = sub.add_parser("select", help="pick an existing member per cluster")
    ps.add_argument("input")
    ps.add_argument("output")
    ps.add_argument("--method", choices=["best", "medoid"], default="medoid")
    _add_backend(ps)
    ps.add_argument("--msms", help="MaxQuant msms.txt (for --method best)")
    ps.add_argument("--psms", help="percolator/crux PSM TSV score source "
                                   "(for --method best; ref search.sh:6)")
    ps.add_argument("--raw-name", help="raw file name for --psms USIs "
                                       "(default: basename of its 'file' column)")
    ps.add_argument("--px-accession", default="PXD004732")
    ps.add_argument("--xcorr-bin", type=float, default=0.1)
    _add_execution(ps)
    ps.add_argument(
        "--qc-report", metavar="FILE",
        help="also compute each representative's mean member cosine and "
        "write the per-cluster QC report here",
    )
    ps.add_argument(
        "--clusters",
        help="MaRaCluster TSV — consume a raw .mzML input directly, no "
        "convert step (--msms then also provides peptide titles)",
    )
    ps.add_argument(
        "--qc-normalization", choices=["none", "sqrt", "log"],
        default="none",
        help="intensity transform for the QC cosine",
    )
    _add_observability(ps)
    ps.set_defaults(fn=cmd_select)

    pv = sub.add_parser("convert", help="build the clustered-MGF interchange file")
    pv.add_argument("input", help="raw spectra (.mgf or .mzML)")
    pv.add_argument("output")
    pv.add_argument("--msms", required=True, help="MaxQuant msms.txt")
    pv.add_argument("--clusters", required=True, help="MaRaCluster TSV")
    pv.add_argument("--raw-name", help="raw file name for USIs")
    pv.add_argument("--px-accession", default="PXD004732")
    pv.set_defaults(fn=cmd_convert)

    pe = sub.add_parser("evaluate", help="quality metrics for representatives")
    pe.add_argument("representatives")
    pe.add_argument("clustered")
    _add_backend(pe)
    pe.add_argument("--report", help="write per-cluster report to this path")
    pe.add_argument(
        "--normalization", choices=["none", "sqrt", "log"], default="none",
        help="intensity transform for the cosine metric",
    )
    pe.add_argument("--format", choices=["json", "csv"], default="json")
    pe.add_argument(
        "--trace-dir", metavar="DIR",
        help="capture a jax.profiler device trace of the evaluate compute "
        "into this directory (view with TensorBoard / Perfetto)",
    )
    pe.set_defaults(fn=cmd_evaluate)

    pm = sub.add_parser(
        "merge-parts",
        help="concatenate multi-host <output>.part<id> shards in order",
    )
    pm.add_argument("output", help="final output path (parts are "
                    "<output>.part00000, <output>.part00001, ...)")
    pm.add_argument("--num-processes", type=int,
                    help="expected part count (refuse to merge fewer)")
    pm.add_argument(
        "--elastic", metavar="DIR|URL",
        help="verify against an elastic run's coordinator store "
        "(shared directory or object-store URL): the plan plus any "
        "work-stealing overlay ranges pin the expected part set (and "
        "the cluster order — split-off tails merge by START, not id), "
        "and every part's size + sha256 is checked against its range "
        "commit marker before any bytes move",
    )
    pm.add_argument(
        "--checkpoint", metavar="BASE",
        help="verify each part against its <BASE>.part<id> schema-2 "
        "resume manifest (size + sha256) from a static multi-host run",
    )
    pm.add_argument(
        "--qc-report", metavar="FILE",
        help="also merge the per-shard <FILE>.part<id> QC reports into "
        "FILE (byte-identical to a single-host serial run's report)",
    )
    pm.add_argument("--remove-parts", action="store_true",
                    help="delete the part files after a successful merge")
    pm.set_defaults(fn=cmd_merge_parts)

    pwu = sub.add_parser(
        "warmup",
        help="AOT-compile every kernel variant in a shape manifest into "
        "the persistent compilation cache (zero fresh compiles on the "
        "next run)",
    )
    pwu.add_argument(
        "manifest",
        help="shape manifest JSON — written next to the compile cache by "
        "consensus/select runs (see docs/performance.md, 'Warm start')",
    )
    pwu.add_argument(
        "--compile-cache", metavar="DIR|off", default=None,
        help="cache directory to populate (default: same resolution as "
        "consensus/select)",
    )
    pwu.add_argument(
        "--jobs", type=int, default=0, metavar="N",
        help="concurrent AOT compiles (default: min(8, cores))",
    )
    pwu.add_argument(
        "--journal", metavar="FILE",
        help="append warmup events (per-kernel compile-vs-cache-hit, "
        "seconds) to this JSONL journal",
    )
    pwu.add_argument(
        "--no-donate", action="store_true",
        help="warm the NON-donating jit twins (match runs that use "
        "--no-donate: the aliasing spec is part of the compiled "
        "executable, so warming the wrong twin populates the wrong "
        "persistent-cache entry)",
    )
    pwu.set_defaults(fn=cmd_warmup)

    psv = sub.add_parser(
        "serve",
        help="long-lived warm-kernel daemon: boot once (compile cache + "
        "AOT shape-manifest warmup), then serve consensus/select jobs "
        "over a local unix socket at warm-request latency (submit with "
        "`specpride submit`; SIGTERM drains gracefully)",
    )
    psv.add_argument(
        "--socket", metavar="PATH", default=None,
        help="unix socket to serve on (default: $SPECPRIDE_SOCKET or "
        "~/.cache/specpride_tpu/serve.sock)",
    )
    psv.add_argument(
        "--max-queue", type=int, default=16, metavar="N",
        help="admission bound: total queued jobs across all clients; at "
        "capacity new submits are rejected with a retriable status "
        "(default 16)",
    )
    psv.add_argument(
        "--workers", type=int, default=0, metavar="N",
        help="concurrent execution lanes, each with its own resident "
        "backend (pinned to a distinct local device on accelerator "
        "hosts; shared platform on CPU-only hosts).  Jobs writing "
        "distinct outputs run concurrently; same-output jobs are "
        "serialized by the conflict guard.  Default 0 = min(#local jax "
        "devices, 4); 1 = the single-lane daemon",
    )
    psv.add_argument(
        "--batch-window", type=float, default=0.0, metavar="MS",
        help="cross-job micro-batching: a worker popping a batch-"
        "eligible job waits up to MS milliseconds collecting further "
        "COMPATIBLE queued jobs (same method + config digest; same "
        "weighted-fair/quota/conflict eligibility as a normal pop) and "
        "runs their cluster work as ONE shared packed-bucket device "
        "dispatch — per-job outputs stay byte-identical to solo runs, "
        "and the shared dispatch is journaled as batch_dispatch.  "
        "Default 0 = off (every job dispatches alone, the PR 10 "
        "behavior)",
    )
    psv.add_argument(
        "--batch-max-clusters", type=int, default=4096, metavar="N",
        help="size bound for one shared dispatch: stop collecting once "
        "the batch's merged cluster count reaches N (default 4096)",
    )
    psv.add_argument(
        "--quota", metavar="CLIENT=WEIGHT[:MAX_INFLIGHT],...",
        help="per-tenant scheduling quotas, e.g. 'teamA=3:2,teamB=1,"
        "*=1:1' ('*' = default for unnamed clients): WEIGHT biases the "
        "weighted-fair scheduler (a weight-3 client gets 3 jobs per "
        "weight-1 job under contention), MAX_INFLIGHT caps the "
        "client's queued+executing jobs — beyond it submissions are "
        "rejected retriable with the quota named (exit 75 via "
        "`specpride submit`).  Default: every client weight 1, no cap",
    )
    psv.add_argument(
        "--compile-cache", metavar="DIR|off", default=None,
        help="persistent XLA compilation cache (same resolution as "
        "consensus/select; resolved ONCE at boot — jobs may not "
        "override it)",
    )
    psv.add_argument(
        "--routing-table", metavar="FILE",
        help="bench-derived kernel-routing override file for the "
        "resident backend",
    )
    psv.add_argument(
        "--layout", choices=["auto", "flat", "bucketized"], default="auto",
        help="resident backend device layout (jobs may not override)",
    )
    psv.add_argument(
        "--force-device", action="store_true",
        help="pin device kernels on CPU-only jax (see consensus --help)",
    )
    psv.add_argument(
        "--precision", choices=["f32", "bf16", "int8"], default="f32",
        help="packed device-channel precision for every lane's resident "
        "backend (see consensus --help; boot-owned — jobs cannot "
        "override it)",
    )
    psv.add_argument(
        "--no-donate", action="store_true",
        help="disable buffer donation on the resident backends (see "
        "consensus --help; boot-owned)",
    )
    psv.add_argument(
        "--warmup", choices=["auto", "manifest", "off"], default="auto",
        help="boot-time AOT warmup from the shape manifest beside the "
        "compile cache: auto warms when one exists, manifest requires "
        "one, off skips (default auto)",
    )
    psv.add_argument(
        "--warmup-manifest", metavar="FILE",
        help="shape manifest path (default: <compile-cache dir>/"
        "shape_manifest.json)",
    )
    psv.add_argument(
        "--warmup-jobs", type=int, default=0, metavar="N",
        help="concurrent boot AOT compiles (default: min(8, cores))",
    )
    psv.add_argument(
        "--watchdog-timeout", type=float, default=0.0, metavar="S",
        help="journal a watchdog_stall when a served job busies the "
        "execution lane longer than S seconds (default 0 = off)",
    )
    psv.add_argument(
        "--journal", metavar="FILE",
        help="daemon lifecycle + per-job serving telemetry (serve_start, "
        "job_queued/job_start/job_done/job_rejected, serve_drain) — "
        "watch live with `specpride stats --follow`",
    )
    psv.add_argument(
        "--journal-rotate-mb", type=float, default=0.0, metavar="N",
        help="rotate the live --journal into numbered segments "
        "(<journal>.1, .2, ...) once it exceeds N megabytes, so a "
        "days-long daemon journal stays bounded; `specpride stats` "
        "(incl. --follow) and the `specpride trace` merger read across "
        "segment boundaries (default 0 = never rotate)",
    )
    psv.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="serve a live Prometheus /metrics endpoint on this port "
        "(0 = ephemeral, read the bound port from the serve_start "
        "journal event or the status op; default: off).  Loopback only "
        "unless --metrics-host widens it",
    )
    psv.add_argument(
        "--metrics-host", default="127.0.0.1", metavar="HOST",
        help="bind address for --metrics-port (default 127.0.0.1 — the "
        "telemetry plane is an operator surface; exposing it beyond "
        "the host is an explicit decision)",
    )
    psv.add_argument(
        "--metrics-out", metavar="FILE",
        help="flush a final Prometheus textfile snapshot of the serving "
        "metrics at SIGTERM drain (same exposition /metrics serves)",
    )
    psv.add_argument(
        "--result-cache", metavar="DIR[:MB]",
        help="content-addressed consensus result cache shared by every "
        "worker lane (boot-owned — jobs cannot carry their own): "
        "repeat submissions of already-computed clusters replay the "
        "stored representative + QC cosine instead of recomputing, "
        "with output bytes identical to an uncached run (see "
        "consensus --help and docs/performance.md)",
    )
    psv.add_argument(
        "--result-store", metavar="DIR|URL",
        help="(with --result-cache) shared second cache tier: a "
        "directory or http(s):// conditional-put object store "
        "(`specpride cas-server`) the whole fleet populates and "
        "consults",
    )
    psv.add_argument(
        "--slo", metavar="METHOD=SECONDS,...",
        help="per-method latency objectives, e.g. "
        "'bin-mean=2,gap-average=3,*=10' ('*' = catch-all): each job's "
        "queue wait + wall is evaluated against its objective, "
        "journaled on job_done (slo_ok / slo_latency_s) and exported "
        "as burn counters on /metrics; render with "
        "`specpride stats --slo`",
    )
    psv.add_argument(
        "--autotune", choices=["off", "observe", "on"], default="off",
        help="closed-loop controller over the daemon's live knobs "
        "(batch window, active worker lanes), driven by the journal's "
        "own telemetry: 'observe' journals every would-be decision "
        "without acting (the safe rollout mode), 'on' also actuates; "
        "every decision is an `autotune` journal event carrying its "
        "evidence — requires --journal; replay with `specpride "
        "autotune-replay` (default off; see docs/autotune.md)",
    )
    psv.add_argument(
        "--autotune-interval", type=float, default=1.0, metavar="S",
        help="controller tick interval in seconds (default 1.0)",
    )
    psv.add_argument(
        "--autotune-batch-window", metavar="LO:HI", default=None,
        help="clamp for the tuned batch window in MILLISECONDS, e.g. "
        "0:50 — the controller never moves --batch-window outside "
        "[LO, HI] (default 0:50)",
    )
    psv.add_argument(
        "--flightrec", choices=["off", "observe", "on"], default="off",
        help="flight recorder: an always-on ring of recent journal "
        "records plus health detectors (SLO-breach streaks, latency "
        "spikes vs EWMA, queue saturation, watchdog stalls, retry "
        "exhaustion, fallback_solo bursts, lease churn).  'observe' "
        "journals each firing as an `incident` event; 'on' also dumps "
        "an atomic diagnostic bundle under --incident-dir; 'off' "
        "constructs no recorder at all.  Requires --journal; audit "
        "with `specpride incident-replay` (default off; see "
        "docs/observability.md)",
    )
    psv.add_argument(
        "--incident-dir", metavar="DIR",
        help="directory for --flightrec on incident bundles (ring "
        "dump, thread stacks, /metrics snapshot, autotune knob state, "
        "config digest, journal tail; read with `specpride incidents`)",
    )
    psv.set_defaults(fn=cmd_serve)

    ppr = sub.add_parser(
        "profile",
        help="capture an on-demand jax.profiler device trace (plus the "
        "daemon-journal window) on a RUNNING warm serve daemon — no "
        "restart, no cold recompile on the next job",
    )
    ppr.add_argument(
        "--socket", metavar="PATH", default=None,
        help="daemon socket (default: $SPECPRIDE_SOCKET or "
        "~/.cache/specpride_tpu/serve.sock)",
    )
    ppr.add_argument(
        "--seconds", type=float, default=3.0, metavar="S",
        help="capture window length (default 3; bounded server-side)",
    )
    ppr.add_argument(
        "--trace-dir", metavar="DIR", default=None,
        help="where the daemon writes the device trace (default: a "
        "fresh temp dir, named in the reply) — view with TensorBoard "
        "or Perfetto",
    )
    ppr.add_argument(
        "--chrome-trace", metavar="FILE", default=None,
        help="also copy the capture's perfetto trace (gzipped "
        "chrome-loadable JSON) to this path",
    )
    ppr.add_argument(
        "--timeout", type=float, default=30.0, metavar="S",
        help="connect/reply margin beyond the capture window "
        "(default 30)",
    )
    ppr.set_defaults(fn=cmd_profile)

    psb = sub.add_parser(
        "submit",
        help="submit one consensus/select job to a serving daemon and "
        "stream its status lines (exit 0 done, 75 retriable rejection, "
        "2 rejected, 1 error)",
    )
    psb.add_argument(
        "--socket", metavar="PATH", default=None,
        help="daemon socket (default: $SPECPRIDE_SOCKET or "
        "~/.cache/specpride_tpu/serve.sock)",
    )
    psb.add_argument(
        "--timeout", type=float, default=30.0, metavar="S",
        help="connect + admission timeout in seconds; once accepted the "
        "job waits unbounded (default 30)",
    )
    psb.add_argument(
        "--client", metavar="NAME", default=None,
        help="scheduling identity for the daemon's weighted-fair queue "
        "and --quota matching (default: a per-process id — one "
        "submitting process = one tenant)",
    )
    psb.add_argument(
        "--retry", type=int, default=0, metavar="N",
        help="resubmit up to N times on a RETRIABLE rejection (queue "
        "full, quota overrun, draining, connect failure — the exit-75 "
        "class), with the robustness layer's exponential backoff + "
        "deterministic jitter between attempts (default 0: fail fast)",
    )
    psb.add_argument(
        "--retry-backoff", type=float, default=0.5, metavar="S",
        help="base backoff before the first resubmit; doubles per "
        "attempt with deterministic jitter (default 0.5)",
    )
    psb.add_argument(
        "--journal", metavar="FILE",
        help="write the CLIENT-side journal shard for this submit: a "
        "clock anchor plus submit/submit:admit/submit:wait spans under "
        "the job's trace_id — `specpride trace --job` merges it with "
        "the daemon and job journals into one causal timeline",
    )
    psb.add_argument(
        "job", nargs=argparse.REMAINDER,
        help="the one-shot CLI argv to run, after --: consensus|select "
        "INPUT OUTPUT [flags] (daemon-owned flags like --compile-cache "
        "and --layout are rejected)",
    )
    psb.set_defaults(fn=cmd_submit)

    pf = sub.add_parser(
        "fleet",
        help="warm-spare autoscaling supervisor for an elastic run: "
        "spawn N rank processes over the argv after --, replace dead "
        "ones, scale spares up/down from heartbeat ages and the "
        "completion horizon (journals rank_spawn/rank_retire)",
    )
    pf.add_argument(
        "--ranks", type=int, default=2, metavar="N",
        help="baseline worker processes to keep running while "
        "uncommitted ranges remain (default 2; 0 = pure-spare mode "
        "supervising externally launched ranks)",
    )
    pf.add_argument(
        "--spares", type=int, default=0, metavar="M",
        help="extra warm workers to spawn when a rank's heartbeat goes "
        "stale (presumed dead/stalled) or the completion horizon "
        "exceeds --scale-horizon (default 0)",
    )
    pf.add_argument(
        "--max-ranks", type=int, default=None, metavar="N",
        help="hard cap on concurrent workers (default ranks + spares)",
    )
    pf.add_argument(
        "--scale-horizon", type=float, default=60.0, metavar="S",
        help="projected seconds of remaining work (ranges left / "
        "commit rate) beyond which spares warm up (default 60)",
    )
    pf.add_argument(
        "--poll", type=float, default=0.5, metavar="S",
        help="supervision loop interval (default 0.5)",
    )
    pf.add_argument(
        "--timeout", type=float, default=None, metavar="S",
        help="abort the fleet after S seconds (default: unbounded)",
    )
    pf.add_argument(
        "--journal", metavar="FILE",
        help="JSONL journal for the supervisor's rank_spawn/rank_retire "
        "decisions (workers journal separately via their own --journal)",
    )
    pf.add_argument(
        "--autotune", choices=["off", "observe", "on"], default="off",
        help="closed-loop controller over the warm-spare count, driven "
        "by live steal pressure (split proposals, stale heartbeats): "
        "'observe' journals every would-be --spares decision without "
        "acting, 'on' also actuates within [0, max-ranks - ranks].  "
        "Requires --journal; every decision is an `autotune` event "
        "(default off; see docs/autotune.md)",
    )
    pf.add_argument(
        "--flightrec", choices=["off", "observe", "on"], default="off",
        help="flight recorder over the supervisor's journal: health "
        "detectors (lease churn, retry exhaustion, ...) journal "
        "`incident` events ('observe') and dump atomic bundles under "
        "--incident-dir ('on').  Requires --journal (default off; see "
        "docs/observability.md)",
    )
    pf.add_argument(
        "--incident-dir", metavar="DIR",
        help="directory for --flightrec on incident bundles (read "
        "with `specpride incidents`)",
    )
    pf.add_argument(
        "job", nargs=argparse.REMAINDER,
        help="the rank argv to supervise, after --: consensus|select "
        "INPUT OUTPUT --elastic DIR|URL [flags] (no --process-id — "
        "workers auto-assign fresh ranks)",
    )
    pf.set_defaults(fn=cmd_fleet)

    pcs = sub.add_parser(
        "cas-server",
        help="in-tree conditional-put/ETag object store (the --elastic "
        "URL backend's reference server; in-memory, for CI/bench/dev)",
    )
    pcs.add_argument(
        "--host", default="127.0.0.1", metavar="HOST",
        help="bind address (default 127.0.0.1)",
    )
    pcs.add_argument(
        "--port", type=int, default=0, metavar="PORT",
        help="bind port (default 0 = ephemeral; the chosen URL prints "
        "on stdout)",
    )
    pcs.add_argument(
        "--url-file", metavar="FILE",
        help="also write the server URL to FILE (for shell scripts "
        "that need it before stdout is line-buffered through a pipe)",
    )
    pcs.set_defaults(fn=cmd_cas_server)

    pst = sub.add_parser(
        "stats",
        help="summarize run journals (accepts base paths; multi-host "
        ".part<rank> shards merge rank-aware like merge-parts)",
    )
    pst.add_argument("journals", nargs="+",
                     help="journal file(s) from --journal runs")
    pst.add_argument("--json", metavar="FILE",
                     help="also write the machine-readable aggregate here")
    pst.add_argument(
        "--top-spans", type=int, default=0, metavar="N",
        help="also render the N slowest tracing spans (self time, count, "
        "p50/p99) from the journals' v2 span events",
    )
    pst.add_argument(
        "--follow", action="store_true",
        help="tail ONE live journal (a serving daemon's or a running "
        "batch job's) and re-render the summary incrementally as events "
        "land; Ctrl-C exits",
    )
    pst.add_argument(
        "--interval", type=float, default=1.0, metavar="S",
        help="poll interval for --follow (default 1s)",
    )
    pst.add_argument(
        "--slo", action="store_true",
        help="also render the per-method SLO table (objective, jobs, "
        "breaches, burn) from a serving daemon's job_done events — "
        "works with --follow for a live view",
    )
    pst.add_argument(
        "--trace", metavar="HEX32", default=None,
        help="render the CRITICAL PATH of one causal trace (by "
        "trace_id) across the given journal shards: per-hop exclusive "
        "seconds from client submit through daemon queue/dispatch, "
        "shared batch, and pipeline spans, on one clock-anchored axis",
    )
    pst.add_argument(
        "--autotune", action="store_true",
        help="also render the controller's decision log (knob, old -> "
        "new, acted, reason) from the journals' autotune events — "
        "works with --follow for a live view",
    )
    pst.add_argument(
        "--incidents", action="store_true",
        help="also render the flight recorder's incident log "
        "(detector, clock, reason, bundled, dedup suppression) from "
        "the journals' v6 incident events — works with --follow for "
        "a live view",
    )
    pst.set_defaults(fn=cmd_stats)

    par = sub.add_parser(
        "autotune-replay",
        help="re-run the autotune policies over a recorded journal and "
        "verify every decision reproduces exactly (same new value, "
        "same reason, refolded signal snapshots) — the determinism "
        "audit for the closed-loop controller",
    )
    par.add_argument(
        "journal",
        help="journal file from an --autotune observe|on run (base "
        "path; rotated segments and .part<rank> shards replay as "
        "independent per-process streams)",
    )
    par.add_argument(
        "--json", metavar="FILE",
        help="also write the machine-readable replay result here",
    )
    par.set_defaults(fn=cmd_autotune_replay)

    pir = sub.add_parser(
        "incident-replay",
        help="refold a recorded journal through the flight recorder's "
        "detector set and verify every journaled incident re-derives "
        "bit-exact (same id, reason, clock, evidence, dedup) — the "
        "determinism audit for the incident plane",
    )
    pir.add_argument(
        "journal",
        help="journal file from a --flightrec observe|on run (base "
        "path; rotated segments and .part<rank> shards replay as "
        "independent per-process streams)",
    )
    pir.add_argument(
        "--json", metavar="FILE",
        help="also write the machine-readable replay result here",
    )
    pir.set_defaults(fn=cmd_incident_replay)

    pin = sub.add_parser(
        "incidents",
        help="read the atomic diagnostic bundles a --flightrec on "
        "host dumped under its --incident-dir",
    )
    pin.add_argument(
        "action", choices=["list", "show", "export"],
        help="list = one line per bundle; show = print one bundle's "
        "manifest (+ evidence files with --files); export = tar one "
        "bundle",
    )
    pin.add_argument(
        "incident_dir", metavar="INCIDENT_DIR",
        help="the --incident-dir a --flightrec on host wrote into",
    )
    pin.add_argument(
        "incident_id", nargs="?", default=None, metavar="INCIDENT_ID",
        help="(show/export) the bundle's incident id — any unique "
        "prefix, as printed by `incidents list`",
    )
    pin.add_argument(
        "--files", action="store_true",
        help="(show) also print every evidence file in the bundle",
    )
    pin.add_argument(
        "--output", metavar="FILE",
        help="(export) tarball path (default "
        "incident-<incident_id>.tar.gz in the current directory)",
    )
    pin.set_defaults(fn=cmd_incidents)

    pt = sub.add_parser(
        "trace",
        help="reconstruct a Chrome trace-event JSON from run journals "
        "(multi-host .part<rank> shards merge onto one timeline, "
        "pid = rank; view in Perfetto or chrome://tracing)",
    )
    pt.add_argument(
        "journals", nargs="+",
        help="journal file(s) or base paths from --journal runs "
        "(a base path expands to its .part<rank> shards)",
    )
    pt.add_argument("-o", "--out", default="trace.json",
                    help="trace-event JSON output path (default trace.json)")
    pt.add_argument(
        "--job", type=int, default=None, metavar="JOBID",
        help="causal mode: reconstruct the ONE trace of this served "
        "job (resolved via the daemon journal's job events) — spans "
        "from every given shard align on one wall axis via clock "
        "anchors, with flow arrows across process tracks",
    )
    pt.add_argument(
        "--trace-id", default=None, metavar="HEX32",
        help="causal mode with an explicit trace id (e.g. harvested "
        "from a /metrics exemplar or a journal event)",
    )
    pt.set_defaults(fn=cmd_trace)

    pl = sub.add_parser(
        "lint",
        help="project-invariant static analysis: lane-safety, "
        "jit-hygiene, journal/metrics/flag/fault-site conformance "
        "(docs/static-analysis.md); exits non-zero on any finding not "
        "in the committed baseline",
    )
    pl.add_argument(
        "root", nargs="?", default=".",
        help="project root to analyze (default: current directory; CI "
        "runs from the repo root)",
    )
    pl.add_argument(
        "--select", metavar="ID[,ID...]",
        help="run only these checkers (see --list for ids)",
    )
    pl.add_argument(
        "--list", action="store_true",
        help="enumerate checkers with one-line descriptions and exit",
    )
    pl.add_argument(
        "--json", metavar="FILE",
        help="write the machine-readable report here ('-' = stdout)",
    )
    pl.add_argument(
        "--baseline", metavar="FILE",
        help="baseline/suppression file (default: <root>/"
        "lint-baseline.json); findings matching an entry don't fail "
        "the run",
    )
    pl.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline file: report every finding as new",
    )
    pl.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings (every "
        "entry then needs a written 'reason' before CI accepts it)",
    )
    pl.set_defaults(fn=cmd_lint)

    pp = sub.add_parser("plot", help="mirror plots for one cluster")
    pp.add_argument("clustered",
                    help="clustered MGF, or a raw .mzML with --clusters")
    pp.add_argument("cluster_id")
    pp.add_argument("out_prefix")
    pp.add_argument("--consensus", help="representatives MGF (vs-consensus mode)")
    pp.add_argument("--peptide", help="peptide for the theoretical mirror")
    pp.add_argument("--clusters",
                    help="MaRaCluster TSV (direct .mzML input, "
                         "ref plot_cluster.py:50-86)")
    pp.add_argument("--msms", help="MaxQuant msms.txt for peptide titles "
                                   "(direct .mzML input)")
    pp.add_argument("--raw-name", help="raw file name for USIs")
    pp.add_argument("--px-accession", default="PXD004732")
    pp.set_defaults(fn=cmd_plot)

    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    configure_logging(args.verbose, args.log_json)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
