"""Utilities.  Observability moved to ``specpride_tpu.observability``;
these re-exports remain for compatibility."""
from specpride_tpu.observability import (
    RunStats,
    configure_logging,
    device_trace,
)

__all__ = ["RunStats", "configure_logging", "device_trace"]
