"""Utilities: observability (logging, counters, timers, profiler hooks)."""
from specpride_tpu.utils.observe import RunStats, configure_logging, device_trace

__all__ = ["RunStats", "configure_logging", "device_trace"]
