"""Compatibility shim: the observability subsystem moved to
``specpride_tpu.observability`` (run journal, metrics registry, stats CLI).
Import from there; this module re-exports the original names so existing
imports keep working."""

from specpride_tpu.observability.stats import (  # noqa: F401
    RunStats,
    _JsonFormatter,
    configure_logging,
    device_trace,
    logger,
)

__all__ = ["RunStats", "configure_logging", "device_trace", "logger"]
