"""DEPRECATED shim — import from ``specpride_tpu.observability`` instead."""
from specpride_tpu.observability.stats import RunStats, configure_logging, device_trace, logger  # noqa: F401,E501
