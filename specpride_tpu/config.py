"""Configuration dataclasses for all merge strategies and metrics.

The reference scatters its parameters across four inconsistent CLI styles,
module constants and hardcoded call-site literals (survey §5 "Config / flag
system"; e.g. ref src/binning.py:294, src/average_spectrum_clustering.py:21-23,
src/most_similar_representative.py:15, src/benchmark.py:8-9).  Here every
knob lives in one frozen dataclass per method, shared by the numpy oracle,
the TPU backend, and the CLI.
"""

from __future__ import annotations

import dataclasses
from typing import Literal


# "pallas" is deliberately NOT a backend: Pallas is a kernel
# implementation detail inside the tpu backend (ops.pallas_kernels),
# selected per-kernel by measurement, not a user-facing execution mode
Backend = Literal["numpy", "tpu"]


def ppm_bin_index(mz, min_mz: float, ppm: float):
    """THE mass-proportional grid formula:
    ``floor(ln(mz / min_mz) / ln(1 + ppm*1e-6))``, float64.  Accepts a
    scalar or an array.  Single home shared by ``BinMeanConfig.n_bins``
    (the bound) and ``ops.quantize.bin_mean_bins`` (peak quantization) so
    an edit to one cannot silently break the other's bin-range contract."""
    import numpy as np

    width = np.log1p(ppm * 1e-6)
    mzf = np.maximum(np.asarray(mz, dtype=np.float64), 1e-300)
    return np.floor(np.log(mzf / min_mz) / width).astype(np.int64)


@dataclasses.dataclass(frozen=True)
class BinMeanConfig:
    """Binned-mean consensus (ref src/binning.py:170 combine_bin_mean).

    ``min_mz``/``max_mz``/``bin_size`` reproduce the hardcoded call at
    ref src/binning.py:294 (100, 2000, 0.02).  ``quorum_fraction`` is the
    0.25 literal at ref src/binning.py:183; quorum = int(n*frac)+1.
    """

    min_mz: float = 100.0
    max_mz: float = 2000.0
    bin_size: float = 0.02
    apply_peak_quorum: bool = True
    quorum_fraction: float = 0.25
    # grid generalization (BASELINE configs[3]): "da" is the reference's
    # fixed-width grid; "ppm" uses mass-proportional bins of ``ppm`` parts
    # per million (``ppm_bin_index`` below — THE single formula, consumed
    # by ``n_bins`` here and by ``ops.quantize.bin_mean_bins`` for peak
    # quantization, so grid and bound cannot drift apart).
    tolerance_mode: Literal["da", "ppm"] = "da"
    ppm: float = 20.0

    def __post_init__(self):
        if self.tolerance_mode == "ppm":
            if not self.ppm > 0:
                raise ValueError(
                    f"tolerance_mode='ppm' needs ppm > 0, got {self.ppm}"
                )
            if not self.min_mz > 0:
                raise ValueError(
                    "tolerance_mode='ppm' needs min_mz > 0 (the grid is "
                    f"logarithmic in mz/min_mz), got {self.min_mz}"
                )
        elif not self.bin_size > 0:
            raise ValueError(f"bin_size must be > 0, got {self.bin_size}")
        if not self.max_mz > self.min_mz:
            raise ValueError(
                f"max_mz ({self.max_mz}) must exceed min_mz ({self.min_mz})"
            )

    @property
    def n_bins(self) -> int:
        if self.tolerance_mode == "ppm":
            return int(ppm_bin_index(self.max_mz, self.min_mz, self.ppm)) + 1
        # ref src/binning.py:172: int((max-min)/binsize) + 1
        return int((self.max_mz - self.min_mz) / self.bin_size) + 1


@dataclasses.dataclass(frozen=True)
class GapAverageConfig:
    """Gap-clustered average consensus
    (ref src/average_spectrum_clustering.py:21-23,26-103).

    ``tail_mode`` documents a deliberate behavioural switch:

    * ``"reference"`` reproduces the reference loop over ``ind_list[1:-1]``
      (ref src/average_spectrum_clustering.py:79-87), which ignores the final
      m/z gap when there are >= 2 gaps, merging the last two peak groups.
    * ``"split"`` honours every gap (the mathematically intended behaviour).
    """

    mz_accuracy: float = 0.01
    dyn_range: float = 1000.0
    min_fraction: float = 0.5
    tail_mode: Literal["reference", "split"] = "reference"
    pepmass: Literal["naive_average", "neutral_average", "lower_median"] = "lower_median"
    rt: Literal["median", "mass_lower_median"] = "median"


@dataclasses.dataclass(frozen=True)
class MedoidConfig:
    """Most-similar (medoid) representative
    (ref src/most_similar_representative.py:13-19,60-111).

    Similarity is an occupancy-grid binned dot product normalised by the
    smaller raw peak count — the capability pyOpenMS
    ``XQuestScores::xCorrelationPrescore(spec1, spec2, 0.1)`` supplies at
    ref src/most_similar_representative.py:15.  ``bin_size`` is that 0.1 Da
    literal.  Bin index is ``floor(mz / bin_size)`` (truncation — what both
    the oracle and the device kernel implement).
    """

    bin_size: float = 0.1


@dataclasses.dataclass(frozen=True)
class BestSpectrumConfig:
    """Best-PSM-score representative (ref src/best_spectrum.py:43-100).

    ``px_accession`` replaces the hardcoded ``mzspec:PXD004732:`` prefix
    (ref src/best_spectrum.py:61-62, marked FIXME there).
    """

    px_accession: str = "PXD004732"
    raw_suffix: str = ".raw"


@dataclasses.dataclass(frozen=True)
class CosineConfig:
    """Binned-cosine quality metric (ref src/benchmark.py:8-29).

    ``mz_unit``/``mz_space`` reproduce ref src/benchmark.py:8-9: bins of
    ~0.005 Da on a grid starting at -mz_space/2.
    """

    mz_unit: float = 1.000508
    mz_space_factor: float = 0.005
    # intensity transform before binning (BASELINE configs[3]): "sqrt"
    # tempers dominant peaks, "log" (log1p) flattens dynamic range —
    # applied identically by the oracle, the native kernel wrapper, and
    # both device packers (ops.quantize.cosine_normalize)
    normalization: Literal["none", "sqrt", "log"] = "none"

    @property
    def mz_space(self) -> float:
        return self.mz_unit * self.mz_space_factor


@dataclasses.dataclass(frozen=True)
class FragmentConfig:
    """b/y-ion annotation (ref src/benchmark.py:40-61 fraction_of_by).

    50 ppm tolerance and the [100, 1400] m/z preprocessing window reproduce
    ref src/benchmark.py:47-52.
    """

    tol: float = 50.0
    tol_mode: Literal["ppm", "Da"] = "ppm"
    min_mz: float = 100.0
    max_mz: float = 1400.0
    ion_types: str = "by"


@dataclasses.dataclass(frozen=True)
class BatchConfig:
    """Bucketing of ragged clusters into packed device batches
    (``data.packed``).

    Each distinct bucket shape is one XLA compilation and one dispatch
    round-trip; fewer buckets mean fewer recompiles/dispatches but more
    padding waste (survey §7 hard part a).
    """

    # coarse: each distinct (k, m) bucket pair is a separate XLA compile of
    # the medoid occupancy/gram kernel AND a dispatch round-trip (~0.1 s on
    # tunneled hosts) — the round-4 medoid bench spent more time in bucket
    # round-trips than in compute, so both axes stay very coarse: padding
    # only costs H2D bytes (GB/s) and low-utilization matmul FLOPs
    member_buckets: tuple[int, ...] = (32, 128)
    # total peaks per cluster (packed layout, data.packed) — one axis of
    # bucket waste instead of two
    total_peak_buckets: tuple[int, ...] = (2048, 8192, 32768)
    # bounds transient host memory per packed batch (the widest bucket
    # materializes (clusters_per_batch, K) f64 host arrays); benchmarks on
    # big-memory hosts pass a larger value explicitly
    clusters_per_batch: int = 1024
