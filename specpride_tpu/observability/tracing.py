"""Hierarchical span tracing: where the time goes INSIDE a run.

The phase timers (``RunStats.phase``) answer "how much total time did
parse/pack/dispatch take"; spans answer "which chunk, which kernel call,
which straggler bucket" — each span is one timed interval with a name,
labels, and a position in the nesting hierarchy.  Three consumers share
one span stream:

* the **run journal** (``--journal``): every finished span is one
  additive v2 ``span`` event (emitted at close, so a killed run simply
  lacks the events for spans still open — nothing to repair);
* **Chrome trace export** (``--chrome-trace FILE`` or
  ``specpride trace JOURNAL...``): trace-event JSON loadable in
  Perfetto / chrome://tracing, multi-host ``.part<rank>`` shards merged
  onto one timeline with ``pid`` = rank;
* **slowest-span analysis** (``specpride stats --top-spans N``): per-name
  self time / count / p50 / p99 without opening a UI.

Clocks: span durations come from ``time.perf_counter`` (monotonic — a
wall-clock jump mid-run cannot corrupt them).  For cross-host merging the
monotonic axis is anchored to the wall clock once per run segment (at
``run_start``), so ranks align on their NTP-synced wall clocks while
within-rank intervals stay monotonic-exact.

Usage: the CLI installs one ``Tracer`` per run (``set_current``); library
code opens spans through the module-level ``span()`` / ``traced()``
helpers, which no-op against a ``NullTracer`` when tracing is off.
"""

from __future__ import annotations

import functools
import json
import os
import re
import threading
import time

from specpride_tpu.observability.journal import (
    NullJournal,
    _json_default,
    expand_parts,
    read_events,
)


# -- trace context -------------------------------------------------------
#
# The v4 causal envelope: a `trace_id` minted once per logical request
# (job admission, elastic run start, one-shot CLI run) plus the span id
# of the hop that spawned the current scope.  The pair threads through
# every process boundary — the serve wire protocol (`"trace"` on
# submit), the coordinator's plan record, and the SPECPRIDE_TRACE env
# handoff for spawned rank processes — so every hop's spans parent into
# ONE cross-process tree the trace merger (observability.traceplane)
# can reassemble.

TRACE_ENV = "SPECPRIDE_TRACE"


def new_trace_id() -> str:
    """A fresh 128-bit trace id (32 lowercase hex chars)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A fresh 64-bit span id (16 lowercase hex chars)."""
    return os.urandom(8).hex()


class TraceContext:
    """One hop's causal coordinates: the trace it belongs to and the
    span id its top-level spans parent under."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    @classmethod
    def mint(cls) -> "TraceContext":
        return cls(new_trace_id(), new_span_id())

    def child(self) -> "TraceContext":
        """The context a spawned hop runs under: same trace, fresh
        parent span id."""
        return TraceContext(self.trace_id, new_span_id())

    def to_env(self) -> str:
        return f"{self.trace_id}:{self.span_id}"

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "parent_span_id": self.span_id}

    @classmethod
    def from_env(cls, value: str | None = None) -> "TraceContext | None":
        """Parse the ``SPECPRIDE_TRACE`` handoff (``trace_id:span_id``);
        None when absent or malformed — a bad handoff must degrade to a
        fresh trace, never crash a rank."""
        if value is None:
            value = os.environ.get(TRACE_ENV)
        if not value:
            return None
        parts = value.strip().split(":")
        if len(parts) != 2:
            return None
        trace_id, span_id = parts
        if re.fullmatch(r"[0-9a-f]{32}", trace_id) and re.fullmatch(
            r"[0-9a-f]{16}", span_id
        ):
            return cls(trace_id, span_id)
        return None

    @classmethod
    def from_wire(cls, obj) -> "TraceContext | None":
        """Parse the submit message's ``trace`` object; None when absent,
        raises ``ValueError`` on a present-but-malformed one (the daemon
        rejects the job — a half-broken trace join is worse than none)."""
        if obj is None:
            return None
        if not isinstance(obj, dict):
            raise ValueError("trace must be an object")
        trace_id = obj.get("trace_id")
        span_id = obj.get("parent_span_id")
        if not (isinstance(trace_id, str)
                and re.fullmatch(r"[0-9a-f]{32}", trace_id)):
            raise ValueError("trace.trace_id must be 32 hex chars")
        if not (isinstance(span_id, str)
                and re.fullmatch(r"[0-9a-f]{16}", span_id)):
            raise ValueError("trace.parent_span_id must be 16 hex chars")
        return cls(trace_id, span_id)


class _NullSpan:
    """Reusable no-op span (one shared instance; carries no state)."""

    __slots__ = ()
    enabled = False

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def note(self, **labels) -> None:
        pass


_NULL_SPAN = _NullSpan()


class NullTracer:
    """No-op stand-in so call sites never branch on 'tracing on?'."""

    enabled = False
    spans: tuple = ()

    def span(self, name: str, **labels) -> _NullSpan:
        return _NULL_SPAN

    def complete(self, name: str, t_start: float, dur_s: float,
                 **labels) -> None:
        pass


class Span:
    """One open interval; a context manager that records itself on exit.

    ``note(**labels)`` may add labels any time before close — the journal
    event is only written when the span finishes."""

    __slots__ = ("tracer", "name", "labels", "t0", "depth", "span_id",
                 "parent_span_id")
    enabled = True

    def __init__(self, tracer: "Tracer", name: str, labels: dict):
        self.tracer = tracer
        self.name = name
        self.labels = labels
        self.span_id = None
        self.parent_span_id = None

    def note(self, **labels) -> None:
        self.labels.update(labels)

    def __enter__(self) -> "Span":
        stack = self.tracer._stack()
        self.depth = len(stack)
        if self.tracer.ctx is not None:
            # causal ids are assigned at OPEN (children must see their
            # parent's id on the stack), journaled at close with the rest
            self.span_id = new_span_id()
            self.parent_span_id = (
                stack[-1].span_id if stack and stack[-1].span_id
                else self.tracer.ctx.span_id
            )
        stack.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        end = time.perf_counter()
        stack = self.tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        self.tracer._record(
            self.name, end, end - self.t0, self.depth, self.labels,
            span_id=self.span_id, parent_span_id=self.parent_span_id,
        )
        return False


class Tracer:
    """Span recorder: nestable context-manager spans on a per-thread
    stack, each emitted as a journal ``span`` event at close and
    (``keep=True``) retained in memory for direct Chrome-trace export.

    The journal envelope's ``mono`` field (emission time ==  span end)
    plus the event's ``dur_s`` reconstruct the interval; ``depth`` is the
    nesting depth at open, informational — consumers derive the true
    hierarchy from time containment, which also places spans recorded
    retroactively via ``complete()`` (e.g. async kernel dispatches timed
    by the backend) under the phase that contained them."""

    enabled = True

    def __init__(self, journal=None, keep: bool = False, ctx=None):
        self.journal = journal if journal is not None else NullJournal()
        self.keep = keep
        # trace context (v4 causal envelope): when set, every span gets
        # a fresh span_id at open and a parent_span_id from the
        # enclosing span (ctx.span_id at stack bottom), journaled with
        # the span event — the cross-process causal tree
        self.ctx: TraceContext | None = ctx
        self.spans: list[dict] = []  # finished spans (when keep)
        # wall/mono anchor pair for exporting kept spans without a journal
        self.t0_wall = time.time()
        self.t0_mono = time.perf_counter()
        self._local = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **labels) -> Span:
        return Span(self, name, labels)

    def attach_journal(self, journal, keep: bool) -> None:
        """Attach the run journal after the fact — the CLI installs the
        tracer BEFORE the journal exists so input parsing is traced.
        Spans finished so far replay into it, each overriding the
        envelope ``mono`` with its original end time so reconstruction
        places it correctly on the anchored timeline; every later span
        streams directly.  ``keep=False`` drops the in-memory copies
        once replayed (no ``--chrome-trace`` export will need them)."""
        self.journal = journal
        for s in self.spans:
            journal.emit("span", **s)
        self.keep = keep
        if not keep:
            self.spans = []

    def complete(self, name: str, t_start: float, dur_s: float,
                 **labels) -> None:
        """Record a span measured externally (``t_start`` from
        ``time.perf_counter()``).  Used where the interval is timed by
        existing instrumentation — per-kernel dispatch timing — rather
        than a ``with`` block."""
        span_id = parent = None
        if self.ctx is not None:
            span_id = new_span_id()
            stack = self._stack()
            parent = (
                stack[-1].span_id if stack and stack[-1].span_id
                else self.ctx.span_id
            )
        self._record(
            name, t_start + dur_s, dur_s, len(self._stack()), labels,
            span_id=span_id, parent_span_id=parent,
        )

    def _record(self, name: str, mono_end: float, dur_s: float,
                depth: int, labels: dict, span_id: str | None = None,
                parent_span_id: str | None = None) -> None:
        # `tid`: the recording thread's lane.  The pipelined executor's
        # packer thread emits spans that GENUINELY overlap the dispatch
        # lane's — without a lane id the Chrome view would stack both
        # lanes on one track and time-containment nesting
        # (aggregate_spans) would credit a packer span running inside a
        # consumer span's interval as its child.  Spans are recorded on
        # their owning thread (Span.__exit__) or the dispatching thread
        # (complete()), so the current thread's lane is the right one.
        rec = {
            "name": name, "dur_s": round(dur_s, 6), "depth": depth,
            "tid": _lane_of_thread(),
        }
        if span_id is not None:
            rec["span_id"] = span_id
        if parent_span_id is not None:
            rec["parent_span_id"] = parent_span_id
        if labels:
            rec["labels"] = dict(labels)
        # the envelope `mono` must be the span's END, not the emit time:
        # retroactive spans (complete(); kernel dispatches) are journaled
        # after their containing phase span closed, and a late `mono`
        # would shift them outside it, breaking time-containment nesting
        self.journal.emit("span", mono=mono_end, **rec)
        if self.keep:
            self.spans.append({**rec, "mono": mono_end})

    def write_chrome_trace(self, path: str, pid: int = 0) -> int:
        """Export the kept spans as Chrome trace-event JSON.  Returns the
        number of span events written."""
        events = []
        for s in self.spans:
            wall = self.t0_wall + (s["mono"] - self.t0_mono)
            events.append(_chrome_span(s, wall, pid))
        meta = [_chrome_process_meta(pid, f"rank {pid}")]
        _dump_trace(meta + events, path)
        return len(events)


# small sequential lane id per recording thread (0 = first recorder, in
# practice the main thread): raw thread idents are pthread addresses whose
# truncation could collide, and full idents make unreadable Chrome tids
_TID_LANES: dict[int, int] = {}
_TID_LANES_LOCK = threading.Lock()


def _lane_of_thread() -> int:
    ident = threading.get_ident()
    lane = _TID_LANES.get(ident)
    if lane is None:
        with _TID_LANES_LOCK:
            lane = _TID_LANES.setdefault(ident, len(_TID_LANES))
    return lane


# -- current-tracer plumbing ---------------------------------------------
#
# Two scopes: the PROCESS-global tracer (one-shot CLI runs, bench, and
# every caller that predates multi-lane serving) and an optional
# THREAD-local override.  A serving worker lane installs its job's
# tracer thread-locally so concurrent jobs' spans land in their OWN
# journals instead of whichever job installed the global last; the
# job's lane threads (pack workers, committer) adopt the creating
# thread's tracer at start (cli wires it), so the per-run behaviour is
# identical to the one-shot CLI's.

_NULL_TRACER = NullTracer()
_current: Tracer | NullTracer = _NULL_TRACER
_current_tls = threading.local()


def current() -> Tracer | NullTracer:
    override = getattr(_current_tls, "tracer", None)
    return override if override is not None else _current


def set_current(tracer: Tracer | None) -> Tracer | NullTracer:
    """Install ``tracer`` PROCESS-wide (None restores the no-op tracer);
    returns the previous one so callers can restore it."""
    global _current
    prev = _current
    # serving lanes never call this — they scope tracers per thread via
    # set_thread_current; the process-wide install happens only on the
    # one-shot CLI path, and the swap itself is a single GIL-atomic
    # store either way
    _current = tracer if tracer is not None else _NULL_TRACER  # lint: ok[lane-safety] one-shot CLI installs process-wide; serve lanes use the tls override
    return prev


def set_thread_current(
    tracer: Tracer | NullTracer | None,
) -> Tracer | NullTracer | None:
    """Install ``tracer`` as THIS thread's tracer override (None removes
    the override, falling back to the process-global tracer); returns
    the previous override for restore-on-exit."""
    prev = getattr(_current_tls, "tracer", None)
    _current_tls.tracer = tracer
    return prev


def span(name: str, **labels):
    """Open a span on the current tracer (no-op when tracing is off)."""
    return current().span(name, **labels)


def traced(name: str, **static_labels):
    """Decorator: run the function under a span (no-op when off)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            tracer = current()
            if not tracer.enabled:
                return fn(*args, **kwargs)
            with tracer.span(name, **static_labels):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# -- journal -> timeline reconstruction ----------------------------------

def _wall_times(events):
    """Yield ``(event, wall_seconds)`` with the monotonic axis anchored
    to the wall clock once per run segment (``run_start``), so trace
    reconstruction is immune to wall-clock jumps mid-run: only the
    anchor uses ``ts``, every interval after it rides ``mono``.  v1
    events (no ``mono``) fall back to raw ``ts``."""
    anchor_ts = anchor_mono = None
    for e in events:
        ts = e.get("ts", 0.0)
        mono = e.get("mono")
        if isinstance(mono, (int, float)):
            if e.get("event") == "run_start" or anchor_mono is None:
                anchor_ts, anchor_mono = ts, mono
            wall = anchor_ts + (mono - anchor_mono)
        else:
            wall = ts
        yield e, wall


def rank_of_path(path: str, default: int = 0) -> int:
    """Rank from a ``.part<id>`` suffix (``.part00001`` or ``.part1``),
    else ``default`` — the Chrome-trace ``pid``."""
    m = re.search(r"\.part(\d+)$", path)
    return int(m.group(1)) if m else default


def _chrome_span(rec: dict, wall_end: float, pid: int) -> dict:
    dur = float(rec["dur_s"])
    return {
        "name": rec["name"],
        "cat": "span",
        "ph": "X",
        "ts": (wall_end - dur) * 1e6,
        "dur": dur * 1e6,
        "pid": pid,
        # one Chrome track per recording thread: pipelined runs put the
        # packer lane and the dispatch lane on separate rows (v1 spans
        # without tid all land on track 0, as before)
        "tid": rec.get("tid", 0),
        "args": {**rec.get("labels", {}), "depth": rec.get("depth", 0)},
    }


def _chrome_process_meta(pid: int, name: str) -> dict:
    return {
        "name": "process_name",
        "ph": "M",
        "ts": 0,
        "pid": pid,
        "tid": 0,
        "args": {"name": name},
    }


def _dump_trace(events: list[dict], path: str) -> None:
    """Write trace-event JSON with the time origin shifted to zero (epoch
    microseconds overflow the viewers' float precision)."""
    t0 = min(
        (e["ts"] for e in events if e.get("ph") != "M"), default=0.0
    )
    for e in events:
        if e.get("ph") != "M":
            e["ts"] = round(e["ts"] - t0, 3)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {"traceEvents": events, "displayTimeUnit": "ms"},
            fh, default=_json_default,
        )
        fh.write("\n")


def chrome_events_from_journal(events: list[dict], pid: int) -> list[dict]:
    """One journal's events as Chrome trace events: ``span`` -> complete
    ("X") slices, every other event an instant ("i") marker on the same
    timeline.  Orphaned spans cannot occur here by construction — a span
    is only journaled once finished, and a line torn by a mid-write kill
    was already dropped (deterministically) by ``read_events``."""
    out = []
    for e, wall in _wall_times(events):
        if e["event"] == "span":
            out.append(_chrome_span(e, wall, pid))
        else:
            args = {
                k: v for k, v in e.items()
                if k not in ("v", "ts", "mono", "event")
            }
            out.append({
                "name": e["event"],
                "cat": "event",
                "ph": "i",
                "s": "t",
                "ts": wall * 1e6,
                "pid": pid,
                "tid": 0,
                "args": args,
            })
    return out


def build_chrome_trace(
    journal_paths: list[str], out_path: str
) -> tuple[int, int, list[str], list[str]]:
    """Reconstruct one Chrome trace from one or more journals, merging
    multi-host ``.part<rank>`` shards onto a single timeline (pid =
    rank).  Returns ``(n_span_events, n_files, warnings, violations)`` —
    a post-mortem tool must still render what it CAN read, so schema
    violations are reported, not fatal; nothing is written only when no
    journal file resolves at all (``n_files == 0``)."""
    files: list[str] = []
    warnings: list[str] = []
    for p in journal_paths:
        got, warn = expand_parts(p)
        files.extend(got)
        warnings.extend(warn)
    trace_events: list[dict] = []
    violations: list[str] = []
    n_spans = 0
    for i, path in enumerate(files):
        events, bad = read_events(path)
        violations.extend(bad)
        pid = rank_of_path(path, default=i)
        trace_events.append(
            _chrome_process_meta(pid, os.path.basename(path))
        )
        chunk = chrome_events_from_journal(events, pid)
        n_spans += sum(1 for e in chunk if e["ph"] == "X")
        trace_events.extend(chunk)
    if files:
        _dump_trace(trace_events, out_path)
    return n_spans, len(files), warnings, violations


# -- slowest-span analysis -----------------------------------------------

def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, round(q * (len(sorted_vals) - 1)))
    return sorted_vals[int(idx)]


def aggregate_spans(event_lists: list[list[dict]]) -> list[dict]:
    """Per-name span statistics over one or more journals' events:
    count, total time, SELF time (total minus directly-contained child
    spans — the number that actually localizes a regression), and
    p50/p99/max duration.  Hierarchy is reconstructed per journal by
    time containment on the anchored timeline, so retroactively recorded
    spans (async kernel dispatches) credit their containing phase.
    Sorted by self time, descending."""
    agg: dict[str, dict] = {}
    for events in event_lists:
        spans = []
        for e, wall in _wall_times(events):
            if e.get("event") != "span":
                continue
            dur = float(e["dur_s"])
            spans.append({
                "name": e["name"], "start": wall - dur, "end": wall,
                "dur": dur, "child": 0.0, "tid": e.get("tid", 0),
            })
        # containment runs PER LANE: a packer-thread span genuinely
        # overlapping a dispatch-lane span (pipelined runs) is parallel
        # work, not a child — cross-lane containment would deflate the
        # containing span's self time by work it never did
        spans.sort(key=lambda s: (s["tid"], s["start"], -s["end"]))
        stack: list[dict] = []
        # 1us containment tolerance: dur_s is journaled at 1us precision,
        # so reconstructed start times carry sub-us rounding error
        for s in spans:
            while stack and (
                stack[-1]["tid"] != s["tid"]
                or stack[-1]["end"] <= s["start"] + 1e-6
            ):
                stack.pop()
            if stack and s["end"] <= stack[-1]["end"] + 1e-6:
                stack[-1]["child"] += s["dur"]
            stack.append(s)
        for s in spans:
            a = agg.setdefault(
                s["name"],
                {"name": s["name"], "count": 0, "total_s": 0.0,
                 "self_s": 0.0, "durs": []},
            )
            a["count"] += 1
            a["total_s"] += s["dur"]
            a["self_s"] += max(s["dur"] - s["child"], 0.0)
            a["durs"].append(s["dur"])
    rows = []
    for a in agg.values():
        durs = sorted(a.pop("durs"))
        rows.append({
            **a,
            "total_s": round(a["total_s"], 6),
            "self_s": round(a["self_s"], 6),
            "p50_s": round(_percentile(durs, 0.50), 6),
            "p99_s": round(_percentile(durs, 0.99), 6),
            "max_s": round(durs[-1], 6),
        })
    rows.sort(key=lambda r: r["self_s"], reverse=True)
    return rows


def render_top_spans(rows: list[dict], n: int, out) -> None:
    """The ``specpride stats --top-spans N`` table."""
    if not rows:
        print("no span events (v2 journals emit them when tracing is on)",
              file=out)
        return
    print(f"TOP {min(n, len(rows))} SPANS by self time:", file=out)
    print(
        f"  {'name':<32} {'count':>7} {'total_s':>10} {'self_s':>10} "
        f"{'p50_ms':>9} {'p99_ms':>9} {'max_ms':>9}", file=out,
    )
    for r in rows[:n]:
        print(
            f"  {r['name']:<32} {r['count']:>7} {r['total_s']:>10.3f} "
            f"{r['self_s']:>10.3f} {r['p50_s'] * 1e3:>9.2f} "
            f"{r['p99_s'] * 1e3:>9.2f} {r['max_s'] * 1e3:>9.2f}",
            file=out,
        )
