"""Run-journal telemetry subsystem (replaces ``utils/observe.py``).

Four pieces:

* ``stats``    — ``RunStats`` counters/phase timers, structured logging,
                 the ``jax.profiler`` ``device_trace`` hook
* ``journal``  — append-only JSONL event stream (``--journal FILE``):
                 typed, versioned events an operator can tail live and
                 post-mortem dead runs from
* ``registry`` — named counters/gauges/histograms with labels, exported
                 as a Prometheus textfile (``--metrics-out FILE``) and as
                 JSON inside the journal's ``run_end`` event
* ``stats_cli``— the ``specpride stats`` command over one or more
                 journals (multi-host ``.part<id>`` shards merge
                 rank-aware like ``merge-parts``)
* ``tracing``  — hierarchical span tracer: nested, labeled, monotonic
                 spans journaled as v2 ``span`` events, exported as
                 Chrome trace-event JSON (``--chrome-trace`` /
                 ``specpride trace``), aggregated by
                 ``specpride stats --top-spans``
* ``exporter`` — LIVE telemetry plane for the serving daemon: an
                 in-process Prometheus ``/metrics`` HTTP endpoint
                 (``specpride serve --metrics-port``), per-method SLO
                 burn accounting (``--slo``), and the strict text-
                 format checker the tests/CI scrape pass share
                 (imported lazily — one-shot runs never pay for it)
* ``detect``   — the flight recorder's health detectors: pure,
                 replayable folds of the journal stream (SLO-breach
                 streaks, latency spikes vs EWMA, queue saturation,
                 watchdog stalls, retry exhaustion, solo bursts,
                 lease churn)
* ``flightrec``— always-on black-box capture (``--flightrec``): a
                 bounded ring of recent journal records plus the
                 detector set; firings journal as v6 ``incident``
                 events and (mode ``on``) dump atomic diagnostic
                 bundles under ``--incident-dir`` (imported lazily,
                 like the exporter)
"""

from specpride_tpu.observability.journal import (
    EVENT_FIELDS,
    SCHEMA_VERSION,
    TRACE_EVENT_FIELDS,
    V6_EVENT_FIELDS,
    Journal,
    NullJournal,
    emit_clock_anchor,
    expand_parts,
    expand_segments,
    open_journal,
    read_events,
    validate_event,
)
from specpride_tpu.observability.tracing import (
    NullTracer,
    TraceContext,
    Tracer,
    build_chrome_trace,
)
from specpride_tpu.observability.registry import (
    MetricsRegistry,
    device_counters_snapshot,
    device_summary,
    export_run_metrics,
)
from specpride_tpu.observability.stats import (
    RunStats,
    configure_logging,
    device_trace,
    logger,
)

__all__ = [
    "EVENT_FIELDS",
    "SCHEMA_VERSION",
    "TRACE_EVENT_FIELDS",
    "V6_EVENT_FIELDS",
    "Journal",
    "MetricsRegistry",
    "NullJournal",
    "NullTracer",
    "RunStats",
    "TraceContext",
    "Tracer",
    "build_chrome_trace",
    "configure_logging",
    "device_counters_snapshot",
    "device_summary",
    "device_trace",
    "emit_clock_anchor",
    "expand_parts",
    "expand_segments",
    "export_run_metrics",
    "logger",
    "open_journal",
    "read_events",
    "validate_event",
]
