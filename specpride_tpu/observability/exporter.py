"""Live telemetry plane: in-process Prometheus exporter + SLO accounting.

Everything the repo had before this module — journal, ``--metrics-out``
textfile, Chrome traces, ``specpride stats`` — is an end-of-run
artifact.  A long-lived ``specpride serve`` daemon is operated from
LIVE metrics: this module gives it

* :class:`ServeTelemetry` — the daemon's resident metric registry plus
  the event hooks (``job_done`` / ``job_rejected`` / SLO evaluation)
  and scrape-time samplers (queue depth, in-flight, the process-wide
  compile-cache / bucket-plan-cache singletons) that keep it current;
* :class:`MetricsExporter` — a background HTTP ``/metrics`` endpoint
  (stdlib ``http.server``, loopback by default, ``--metrics-port`` on
  ``specpride serve``) serving the Prometheus text exposition sampled
  at scrape time;
* :func:`parse_slo_spec` — the ``--slo method=seconds,...`` parser; a
  job's latency objective is evaluated per job (queue wait + wall),
  journaled on ``job_done`` and exposed as burn counters;
* :func:`parse_exposition` / :func:`validate_exposition` — a strict
  text-format checker shared by the tests and the CI scrape pass, so
  the endpoint can never drift from what a Prometheus scraper parses.

Thread contract: the exporter renders on HTTP handler threads while the
daemon's worker and reader threads update the registries — safe because
``MetricsRegistry`` locks per metric (see ``registry.py``).  Counters
here are cumulative over the daemon's lifetime (Prometheus semantics);
per-job attribution stays with the journal's snapshot-and-diff deltas.
"""

from __future__ import annotations

import http.server
import os
import re
import threading

from specpride_tpu.observability.registry import MetricsRegistry
from specpride_tpu.observability.stats import logger

# seconds buckets sized for SERVED JOBS (queue wait + execution wall):
# sub-second warm requests up to multi-minute cold/huge ones — a coarser
# ladder than the dispatch-latency DEFAULT_BUCKETS
JOB_SECONDS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


# -- SLO specification --------------------------------------------------


def parse_slo_spec(spec: str | None) -> dict[str, float]:
    """``--slo method=seconds,...`` -> ``{method: objective_seconds}``.

    ``*`` is the catch-all objective for methods not named explicitly.
    Raises ``ValueError`` on malformed entries (the CLI turns it into a
    usage error at boot, never mid-serve)."""
    out: dict[str, float] = {}
    if not spec:
        return out
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        method, sep, value = item.partition("=")
        method = method.strip()
        if not sep or not method:
            raise ValueError(
                f"--slo entry {item!r} is not method=seconds"
            )
        try:
            seconds = float(value)
        except ValueError:
            raise ValueError(
                f"--slo {method}: {value!r} is not a number of seconds"
            ) from None
        if not seconds > 0:
            raise ValueError(
                f"--slo {method}: objective must be > 0 (got {seconds})"
            )
        out[method] = seconds
    return out


def slo_objective(slo: dict[str, float], method: str | None) -> float | None:
    """The objective that applies to ``method`` (explicit beats ``*``),
    or None when no SLO covers it."""
    if method is not None and method in slo:
        return slo[method]
    return slo.get("*")


# -- the daemon's live registry -----------------------------------------


# the pre-register-at-0 contract, machine-checked by `specpride lint`
# (metrics-conformance): every counter/gauge registered in a telemetry
# __init__ in this module whose name matches one of these families must
# be zero-initialized there, so the series exist from the first scrape
# through the final --metrics-out drain snapshot — a 0-valued row beats
# an absent one for rate() queries and for auditing that a feature
# never fired.  Histograms are exempt (they appear with the first
# observe by design).
PRE_REGISTERED_FAMILIES = (
    "specpride_serve_batch_*",
    "specpride_h2d_bytes_total",
    "specpride_d2h_bytes_total",
    "specpride_autotune_*",
    "specpride_incidents_*",
    "specpride_result_cache_*",
)

# the daemon-hosted autotune knobs: their current-value gauges and
# decision counters pre-register at 0 for BOTH label values of `acted`,
# so "the controller never moved this knob" is an auditable 0-valued
# series, not an absent one
AUTOTUNE_KNOBS = ("batch_window_ms", "workers")


class ServeTelemetry:
    """Resident metric state for one serving daemon.

    The daemon calls the ``job_*`` hooks from its worker/reader threads
    as events happen; scrape-time state (queue depth, in-flight,
    uptime, the process-wide cache singletons) is pulled by
    :meth:`exposition` via the ``sampler`` callback the daemon installs
    — so a scrape is always CURRENT, not a stale end-of-job snapshot.

    ``extra_registries`` ride along in the exposition (the daemon passes
    its resident backend's registry, so per-kernel dispatch counters,
    the dispatch-latency histogram and the device peak-memory watermark
    are served live).  Metric names across registries must be disjoint
    — ``specpride_serve_*`` here vs ``specpride_*`` on the backend."""

    def __init__(
        self,
        slo: dict[str, float] | None = None,
        extra_registries: tuple = (),
        worker_registries: "dict[str, MetricsRegistry] | None" = None,
    ):
        self.slo = dict(slo or {})
        self.extra_registries = tuple(extra_registries)
        # worker-pool daemons: each lane's resident backend registry
        # carries the SAME metric names, so they render through
        # registry.render_labeled — one TYPE header per metric, a
        # worker="<id>" label per series (extra_registries would emit
        # colliding duplicate series/TYPE lines)
        self.worker_registries = dict(worker_registries or {})
        # the daemon installs a fn(telemetry) that refreshes live gauges
        # (queue depth, in-flight, uptime) right before each render
        self.sampler = None
        self._lock = threading.Lock()  # guards the singleton-sync deltas
        # one render at a time: the sampler's clear/zero-then-set gauge
        # refresh must not interleave with another scrape's render
        # (ThreadingHTTPServer runs concurrent GETs), or a parallel
        # scrape could serve a spurious idle/empty gauge view
        self._render_lock = threading.Lock()
        self._singletons_last: dict[str, float] = {}
        r = self.registry = MetricsRegistry()
        self.jobs_done = r.counter(
            "specpride_serve_jobs_done_total",
            "served jobs that completed successfully",
            labels=("command", "method"),
        )
        self.jobs_failed = r.counter(
            "specpride_serve_jobs_failed_total",
            "served jobs that errored",
            labels=("command", "method"),
        )
        self.jobs_rejected = r.counter(
            "specpride_serve_jobs_rejected_total",
            "submissions rejected at admission (by reason)",
            labels=("reason",),
        )
        self.job_wall = r.histogram(
            "specpride_serve_job_wall_seconds",
            "execution wall seconds per served job",
            labels=("method",), buckets=JOB_SECONDS_BUCKETS,
        )
        self.job_queue_wait = r.histogram(
            "specpride_serve_job_queue_wait_seconds",
            "admission-to-execution queue wait per served job",
            labels=("method",), buckets=JOB_SECONDS_BUCKETS,
        )
        self.lane_busy = r.counter(
            "specpride_serve_lane_busy_seconds_total",
            "per-lane busy seconds across served jobs (pack worker pool / "
            "dispatch / ordered write lane)",
            labels=("lane",),
        )
        self.queue_depth = r.gauge(
            "specpride_serve_queue_depth", "jobs queued for execution"
        )
        self.queue_depth_client = r.gauge(
            "specpride_serve_queue_depth_client",
            "queued jobs per scheduling client",
            labels=("client",),
        )
        self.inflight_total = r.gauge(
            "specpride_serve_inflight",
            "jobs on the execution lane right now (0 or 1)",
        )
        self.inflight = r.gauge(
            "specpride_serve_inflight_jobs",
            "jobs on the execution lane right now, by job labels "
            "(0 or 1)",
            labels=("command", "method", "backend"),
        )
        self.uptime = r.gauge(
            "specpride_serve_uptime_seconds", "seconds since daemon boot"
        )
        # worker pool (PR 10): lane count, per-lane occupancy sampled at
        # scrape time (clear-and-set over the fixed worker set — idle
        # lanes read 0), and per-lane busy seconds folded per job — the
        # lane-utilization trio an operator sizes --workers from
        self.workers = r.gauge(
            "specpride_serve_workers",
            "execution lanes in the worker pool",
        )
        self.inflight_worker = r.gauge(
            "specpride_serve_inflight_worker",
            "jobs executing on each worker lane right now (0 or 1)",
            labels=("worker",),
        )
        self.worker_busy = r.counter(
            "specpride_serve_worker_busy_seconds_total",
            "execution wall seconds each worker lane spent on served "
            "jobs",
            labels=("worker",),
        )
        self.slo_jobs = r.counter(
            "specpride_serve_slo_jobs_total",
            "served jobs evaluated against a latency objective",
            labels=("method",),
        )
        self.slo_breaches = r.counter(
            "specpride_serve_slo_breaches_total",
            "served jobs whose latency (queue wait + wall) exceeded their "
            "objective — the SLO burn counter",
            labels=("method",),
        )
        slo_objective_g = r.gauge(
            "specpride_serve_slo_objective_seconds",
            "configured per-method latency objective",
            labels=("method",),
        )
        for method, seconds in self.slo.items():
            slo_objective_g.set(seconds, method=method)
        # cross-job micro-batching (serve.batcher): shared-dispatch
        # accounting.  Counters and the occupancy gauge pre-register at
        # 0 so the series exist from the first scrape through the final
        # --metrics-out drain snapshot — a 0-valued row beats an absent
        # one for rate() queries AND for auditing that batching never
        # fired (the histograms appear with the first dispatch)
        self.batch_dispatches = r.counter(
            "specpride_serve_batch_dispatches_total",
            "shared packed-bucket device dispatches coalescing work "
            "from multiple jobs",
        )
        self.batch_jobs = r.counter(
            "specpride_serve_batch_jobs_total",
            "served jobs whose compute rode a shared batch dispatch",
        )
        self.batch_clusters = r.counter(
            "specpride_serve_batch_clusters_total",
            "clusters computed through shared batch dispatches",
        )
        self.batch_jobs_hist = r.histogram(
            "specpride_serve_batch_jobs_per_dispatch",
            "jobs coalesced into each shared dispatch",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
        )
        self.batch_window_wait = r.histogram(
            "specpride_serve_batch_window_wait_seconds",
            "batch-collection time per shared dispatch (companion wait "
            "bounded by --batch-window, plus member input parses)",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0),
        )
        self.batch_occupancy = r.gauge(
            "specpride_serve_batch_occupancy",
            "bucket occupancy (real rows / padded rows) of the most "
            "recent shared dispatch",
        )
        self.batch_dispatches.inc(0)
        self.batch_jobs.inc(0)
        self.batch_clusters.inc(0)
        self.batch_occupancy.set(0.0)
        # closed-loop autotune (specpride_tpu.autotune): per-knob
        # current value + decision counters, mirrored from every
        # journaled `autotune` event by the controller
        self.autotune_knob = r.gauge(
            "specpride_autotune_knob",
            "current value of each controller-managed knob",
            labels=("knob",),
        )
        self.autotune_decisions = r.counter(
            "specpride_autotune_decisions_total",
            "autotune decisions journaled, by knob and whether the "
            "controller acted (mode on) or only observed",
            labels=("knob", "acted"),
        )
        for knob in AUTOTUNE_KNOBS:
            self.autotune_knob.set(0.0, knob=knob)
            self.autotune_decisions.inc(0, knob=knob, acted="true")
            self.autotune_decisions.inc(0, knob=knob, acted="false")
        # flight-recorder incident plane: one firing counter + one
        # dedup-suppression counter per detector, pre-registered at 0
        # for every detector in the catalog so "this detector never
        # fired" is an auditable 0-valued series
        from specpride_tpu.observability.detect import DETECTOR_NAMES
        self.incidents = r.counter(
            "specpride_incidents_total",
            "flight-recorder incidents journaled, by detector",
            labels=("detector",),
        )
        self.incidents_suppressed = r.counter(
            "specpride_incidents_suppressed_total",
            "detector firings suppressed by the flight recorder's "
            "per-detector dedup cooldown, by detector",
            labels=("detector",),
        )
        for det in DETECTOR_NAMES:
            self.incidents.inc(0, detector=det)
            self.incidents_suppressed.inc(0, detector=det)
        # device transfer rollups (memory-bandwidth campaign): summed
        # across worker-lane backend registries by delta at scrape time
        # (sync_singletons); pre-registered at 0 so a daemon that never
        # dispatched still exposes auditable byte series
        r.counter(
            "specpride_h2d_bytes_total",
            "bytes shipped host->device across all worker lanes",
        ).inc(0)
        r.counter(
            "specpride_d2h_bytes_total",
            "bytes fetched device->host across all worker lanes",
        ).inc(0)
        # content-addressed result cache: process-lifetime counters
        # mirrored from cache.result_cache.totals() by delta at scrape
        # time (sync_singletons); pre-registered at 0 so a daemon booted
        # without --result-cache still exposes an auditable all-zero
        # cache surface
        r.counter(
            "specpride_result_cache_hits_total",
            "consensus clusters served from the result cache "
            "(compute skipped)",
        ).inc(0)
        r.counter(
            "specpride_result_cache_misses_total",
            "consulted clusters the result cache could not serve",
        ).inc(0)
        r.counter(
            "specpride_result_cache_populated_total",
            "result-cache entries written after QC",
        ).inc(0)
        r.counter(
            "specpride_result_cache_evictions_total",
            "local-tier LRU evictions forced by the size cap",
        ).inc(0)
        r.counter(
            "specpride_result_cache_bytes_saved_total",
            "peak bytes result-cache hits did not recompute",
        ).inc(0)
        r.counter(
            "specpride_result_cache_shared_hits_total",
            "result-cache hits served by the shared store tier",
        ).inc(0)
        r.counter(
            "specpride_result_cache_corrupt_total",
            "result-cache entries quarantined on digest mismatch "
            "(served as misses, never as results)",
        ).inc(0)

    # -- event hooks (worker / reader threads) -------------------------

    def job_rejected(self, reason: str) -> None:
        self.jobs_rejected.inc(1, reason=reason)

    def autotune_decision(self, *, knob: str, value, acted: bool) -> None:
        """Mirror one journaled ``autotune`` event into the live plane:
        the knob gauge tracks the value in effect AFTER the decision
        (the old value when the controller only observed)."""
        if isinstance(value, (int, float)):
            self.autotune_knob.set(float(value), knob=knob)
        self.autotune_decisions.inc(
            1, knob=knob, acted="true" if acted else "false"
        )

    def incident(self, *, detector: str, suppressed: int = 0) -> None:
        """Mirror one journaled ``incident`` event into the live plane
        (the suppression counter catches up lazily: dedup-suppressed
        firings are accounted when the NEXT incident on that detector
        journals, same as the event's ``suppressed`` field)."""
        self.incidents.inc(1, detector=detector)
        if suppressed:
            self.incidents_suppressed.inc(
                int(suppressed), detector=detector
            )

    def batch_dispatch(
        self, *, n_jobs: int, n_clusters: int, window_wait_s: float,
        occupancy_frac: float,
    ) -> None:
        """Fold one shared cross-job dispatch into the live plane (the
        journal's ``batch_dispatch`` event carries the same numbers)."""
        self.batch_dispatches.inc(1)
        self.batch_jobs.inc(int(n_jobs))
        self.batch_clusters.inc(int(n_clusters))
        self.batch_jobs_hist.observe(float(n_jobs))
        self.batch_window_wait.observe(float(window_wait_s))
        self.batch_occupancy.set(float(occupancy_frac))

    def job_done(
        self, *, command: str, method: str | None, status: str,
        wall_s: float, queue_wait_s: float, summary: dict | None = None,
        worker: int | None = None, trace_id: str | None = None,
    ) -> dict:
        """Fold one finished job in; returns the SLO fields (empty when
        no objective covers the method) for the daemon to journal on its
        ``job_done`` event.  ``trace_id`` rides the wall/queue-wait
        histogram observations as an OpenMetrics exemplar, so a latency
        outlier on ``/metrics`` is one ``specpride trace --trace-id``
        away from its full cross-process timeline."""
        m = method or "-"
        exemplar = {"trace_id": trace_id} if trace_id else None
        if status == "done":
            self.jobs_done.inc(1, command=command, method=m)
        else:
            self.jobs_failed.inc(1, command=command, method=m)
        self.job_wall.observe(wall_s, exemplar=exemplar, method=m)
        self.job_queue_wait.observe(
            queue_wait_s, exemplar=exemplar, method=m
        )
        if worker is not None:
            self.worker_busy.inc(max(float(wall_s), 0.0),
                                 worker=str(worker))
        self._fold_lanes(summary or {})
        objective = slo_objective(self.slo, method)
        if objective is None:
            return {}
        latency = wall_s + queue_wait_s
        ok = latency <= objective
        self.slo_jobs.inc(1, method=m)
        if not ok:
            self.slo_breaches.inc(1, method=m)
        return {
            "slo_objective_s": objective,
            "slo_latency_s": round(latency, 4),
            "slo_ok": ok,
        }

    def _fold_lanes(self, summary: dict) -> None:
        """Per-lane busy seconds from one job's stats summary: the
        multi-lane executor's span accounting (``pipeline.pack_busy_s``
        per worker, ``write_busy_s``) when the job pipelined, the plain
        phase timers otherwise; the dispatch lane is the consumer
        thread's compute phase either way."""
        phases = summary.get("phases_s") or {}
        pipeline = summary.get("pipeline") or {}
        pack = (
            sum(pipeline["pack_busy_s"])
            if pipeline.get("pack_busy_s")
            else phases.get("pack", 0.0)
        )
        write = (
            pipeline["write_busy_s"]
            if pipeline.get("async_write")
            else phases.get("write", 0.0)
        )
        dispatch = phases.get("compute", 0.0)
        for lane, busy in (
            ("pack", pack), ("dispatch", dispatch), ("write", write),
        ):
            if busy and busy > 0:
                self.lane_busy.inc(float(busy), lane=lane)

    # -- scrape-time state ---------------------------------------------

    def sync_singletons(self) -> None:
        """Mirror the process-wide warm-start singletons into Prometheus
        counters: compile-cache hits/misses/saved-seconds and bucket-
        plan-cache traffic.  The singletons are already monotone, so the
        mirror incs by delta since the last scrape — never a set, which
        Counter (correctly) refuses."""
        from specpride_tpu.cache import result_cache as rc_mod
        from specpride_tpu.data.packed import plan_cache_info
        from specpride_tpu.serve import ingest_cache
        from specpride_tpu.warmstart import cache as ws_cache

        cc = ws_cache.counters_snapshot()
        pc = plan_cache_info()
        ic = ingest_cache.info()
        rc = rc_mod.totals()
        totals = {
            "specpride_compile_cache_hits_total": (
                cc["hits"], "persistent compile-cache hits"),
            "specpride_compile_cache_misses_total": (
                cc["misses"], "persistent compile-cache misses "
                "(fresh XLA compiles)"),
            "specpride_compile_cache_requests_total": (
                cc["requests"], "compile requests consulting the "
                "persistent cache"),
            "specpride_compile_cache_saved_seconds_total": (
                cc["saved_s"], "compile seconds avoided by persistent-"
                "cache hits"),
            "specpride_plan_cache_hits_total": (
                pc["hits"], "bucket-plan cache hits"),
            "specpride_plan_cache_misses_total": (
                pc["misses"], "bucket-plan cache misses"),
            "specpride_serve_ingest_cache_hits_total": (
                ic["hits"], "served jobs whose parsed input was "
                "resident (parse skipped)"),
            "specpride_serve_ingest_cache_misses_total": (
                ic["misses"], "served eager parses that populated the "
                "ingest cache"),
            "specpride_result_cache_hits_total": (
                rc["hits"], "consensus clusters served from the result "
                "cache (compute skipped)"),
            "specpride_result_cache_misses_total": (
                rc["misses"], "consulted clusters the result cache "
                "could not serve"),
            "specpride_result_cache_populated_total": (
                rc["populated"], "result-cache entries written after "
                "QC"),
            "specpride_result_cache_evictions_total": (
                rc["evictions"], "local-tier LRU evictions forced by "
                "the size cap"),
            "specpride_result_cache_bytes_saved_total": (
                rc["bytes_saved"], "peak bytes result-cache hits did "
                "not recompute"),
            "specpride_result_cache_shared_hits_total": (
                rc["shared_hits"], "result-cache hits served by the "
                "shared store tier"),
            "specpride_result_cache_corrupt_total": (
                rc["corrupt"], "result-cache entries quarantined on "
                "digest mismatch (served as misses, never as results)"),
        }
        # device transfer totals: the per-lane backend registries each
        # count H2D/D2H bytes (specpride_bytes_*_total); mirror their
        # SUM as serve-level counters by delta, exactly like the
        # compile-cache series — backend registries stay resident and
        # monotone in a daemon, so the sum is too
        byte_srcs = list(self.extra_registries) + list(
            self.worker_registries.values()
        )
        totals["specpride_h2d_bytes_total"] = (
            sum(
                r.sum_counter("specpride_bytes_h2d_total")
                for r in byte_srcs
            ),
            "bytes shipped host->device across all worker lanes",
        )
        totals["specpride_d2h_bytes_total"] = (
            sum(
                r.sum_counter("specpride_bytes_d2h_total")
                for r in byte_srcs
            ),
            "bytes fetched device->host across all worker lanes",
        )
        with self._lock:
            for name, (total, help_) in totals.items():
                last = self._singletons_last.get(name, 0.0)
                if total > last:
                    self.registry.counter(name, help_).inc(total - last)
                self._singletons_last[name] = max(float(total), last)
        self.registry.gauge(
            "specpride_plan_cache_size", "bucket plans resident in cache"
        ).set(pc["size"])

    def exposition(self) -> str:
        """The full Prometheus text exposition, sampled NOW."""
        with self._render_lock:
            sampler = self.sampler
            if sampler is not None:
                sampler(self)
            self.sync_singletons()
            parts = [self.registry.to_prometheus_text()]
            parts.extend(
                r.to_prometheus_text() for r in self.extra_registries
            )
            if self.worker_registries:
                from specpride_tpu.observability.registry import (
                    render_labeled,
                )

                parts.append(
                    render_labeled(self.worker_registries, label="worker")
                )
            return "".join(parts)

    def write_textfile(self, path: str) -> None:
        """Atomic snapshot of the current exposition — the daemon's
        final ``--metrics-out`` flush at SIGTERM drain."""
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(self.exposition())
        os.replace(tmp, path)


# -- elastic rank liveness ----------------------------------------------


class ElasticTelemetry:
    """Live per-rank liveness for an elastic multi-host run
    (``--elastic`` + ``--metrics-port``): the fleet view sampled per
    scrape from the coordinator's shared directory, so a dying rank is
    visible on ``/metrics`` (its ``specpride_rank_heartbeat_age_seconds``
    climbs past the lease TTL) BEFORE any work is lost, and every
    reassignment this rank performed is a counter an alert can burn on.

    ``extra_registries`` ride along in the exposition (the CLI passes
    the backend's device registry, so the rank's own dispatch traffic is
    scrapeable too)."""

    def __init__(self, coordinator, extra_registries: tuple = ()):
        self.coord = coordinator
        self.extra_registries = tuple(extra_registries)
        self._render_lock = threading.Lock()
        self._counters_last = {
            "expires": 0.0, "reassigns": 0.0, "splits": 0.0,
            "steals": 0.0,
        }
        r = self.registry = MetricsRegistry()
        self.hb_age = r.gauge(
            "specpride_rank_heartbeat_age_seconds",
            "seconds since each rank's last heartbeat (sampled from the "
            "coordinator directory at scrape time; an age past the "
            "lease TTL means the rank is presumed dead)",
            labels=("rank",),
        )
        self.ranges_total = r.gauge(
            "specpride_elastic_ranges",
            "chunk ranges in this run's work plan",
        )
        self.ranges_committed = r.gauge(
            "specpride_elastic_ranges_committed",
            "chunk ranges with a commit marker (run completes at "
            "committed == total)",
        )
        self.rank_gauge = r.gauge(
            "specpride_elastic_rank",
            "this process's rank id (constant; a join key for alerts)",
        )
        self.lease_expires = r.counter(
            "specpride_elastic_lease_expires_total",
            "expired peer leases THIS rank observed",
        )
        self.reassigns = r.counter(
            "specpride_elastic_reassignments_total",
            "dead ranks' chunk ranges THIS rank reclaimed",
        )
        self.splits = r.counter(
            "specpride_elastic_lease_splits_total",
            "live work-stealing splits THIS rank ratified as donor "
            "(its range was cut and the tail handed to a faster peer)",
        )
        self.steals = r.counter(
            "specpride_elastic_steals_total",
            "split-off tails THIS rank claimed from slower live peers",
        )

    def health(self) -> tuple[bool, str]:
        """Readiness for ``GET /healthz`` on an elastic rank: degraded
        while a PEER's heartbeat has gone stale past TTL + grace with
        uncommitted work remaining (the fleet supervisor's scale-up
        signal, now visible to load balancers too).  A peer that
        STOPPED cleanly (the final ``stopped`` heartbeat — a retired
        spare, a rank out of claimable work) is not stale, however old
        its last beat: degrading every survivor over a healthy exit
        would have load balancers pulling good ranks."""
        coord = self.coord
        threshold = coord.ttl + getattr(coord, "grace", 0.0)
        stale = sorted(
            r
            for r, (age, stopped) in coord.rank_heartbeat_states().items()
            if age > threshold and not stopped
        )
        done, total = coord.done_count(), len(coord.ranges)
        bits = [f"rank={coord.rank}", f"ranges_committed={done}/{total}"]
        if stale and done < total:
            return False, (
                "stale_ranks=" + ",".join(str(r) for r in stale)
                + " " + " ".join(bits)
            )
        return True, " ".join(bits)

    def exposition(self) -> str:
        with self._render_lock:
            coord = self.coord
            # per-rank heartbeat ages: clear-and-set so a departed
            # rank's final (huge) age doesn't linger as a stale series
            # forever — its disappearance IS the signal once its ranges
            # are reassigned
            self.hb_age.clear()
            for rank, age in coord.rank_heartbeat_ages().items():
                self.hb_age.set(round(age, 3), rank=str(rank))
            self.ranges_total.set(len(coord.ranges))
            self.ranges_committed.set(coord.done_count())
            self.rank_gauge.set(coord.rank)
            for counter, attr, key in (
                (self.lease_expires, "lease_expires_observed", "expires"),
                (self.reassigns, "reassignments", "reassigns"),
                (self.splits, "lease_splits", "splits"),
                (self.steals, "steals", "steals"),
            ):
                total = float(getattr(coord, attr, 0))
                last = self._counters_last[key]
                if total > last:
                    counter.inc(total - last)
                self._counters_last[key] = max(total, last)
            # counters must exist from the first scrape (a 0-valued
            # series beats an absent one for rate() queries)
            self.lease_expires.inc(0)
            self.reassigns.inc(0)
            self.splits.inc(0)
            self.steals.inc(0)
            parts = [self.registry.to_prometheus_text()]
            parts.extend(
                r.to_prometheus_text() for r in self.extra_registries
            )
            return "".join(parts)


# -- the HTTP endpoint --------------------------------------------------


class MetricsExporter:
    """Background ``/metrics`` HTTP endpoint over a render callback.

    Binds ``host:port`` (port 0 = ephemeral; read the bound port back
    from ``.port``), serves ``GET /metrics`` with the Prometheus text
    content type and ``GET /healthz`` with a one-line readiness body, on
    a daemon thread pool (``ThreadingHTTPServer``) so a slow scraper
    never blocks the next one.  Loopback by default: the telemetry
    plane is an OPERATOR surface, exposing it beyond the host is an
    explicit ``--metrics-host`` decision.

    ``health`` (optional): a callback returning ``(ok, detail)`` —
    ``/healthz`` answers ``200 ok <detail>`` or ``503 degraded
    <detail>``, so a fleet supervisor or load balancer gets a REAL
    per-lane readiness signal (the serving daemon wires its watchdog's
    stalled-lane view in; without a callback the endpoint stays the
    old unconditional liveness 200)."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self, render, host: str = "127.0.0.1", port: int = 0,
                 health=None):
        self._render = render
        self._health = health
        self.host = host
        self._requested_port = port
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            return self._requested_port
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsExporter":
        render = self._render
        health = self._health
        content_type = self.CONTENT_TYPE

        class _Handler(http.server.BaseHTTPRequestHandler):
            # the exporter must never spam the daemon's stderr per scrape
            def log_message(self, fmt, *args):  # noqa: A002 - stdlib sig
                pass

            def _reply(self, body: bytes, ctype: str,
                       code: int = 200) -> None:
                # a scraper with a short timeout may drop the connection
                # mid-body: that's its problem, not a stderr traceback
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    pass

            def do_GET(self):  # noqa: N802 - stdlib naming
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    try:
                        body = render().encode("utf-8")
                    except Exception as e:  # noqa: BLE001 - 500, not a crash
                        logger.warning("metrics render failed: %s", e)
                        self.send_error(500, f"render failed: {e}")
                        return
                    self._reply(body, content_type)
                elif path == "/healthz":
                    if health is None:
                        self._reply(b"ok\n", "text/plain")
                        return
                    try:
                        ok, detail = health()
                    except Exception as e:  # noqa: BLE001 - report, not crash
                        ok, detail = False, f"health probe failed: {e}"
                    body = (
                        ("ok" if ok else "degraded")
                        + (f" {detail}" if detail else "") + "\n"
                    ).encode("utf-8")
                    # 503 on degraded: the readiness semantics load
                    # balancers and fleet supervisors key off
                    self._reply(
                        body, "text/plain", code=200 if ok else 503
                    )
                else:
                    self.send_error(404, "only /metrics and /healthz")

        class _Server(http.server.ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # socketserver prints a full traceback here by default —
                # an aborted scrape (BrokenPipeError past the handler's
                # own guard) must stay silent on the daemon's stderr
                pass

        self._httpd = _Server((self.host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="specpride-metrics-exporter", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._httpd = None
        self._thread = None


# -- strict text-format checker (tests + CI) ----------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)(?: (?P<ts>-?\d+))?$"
)
_LABEL_RE = re.compile(
    r'(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"'
)
# OpenMetrics exemplar (after the ` # ` split): {labels} value [ts]
_EXEMPLAR_RE = re.compile(
    r"^\{(?P<labels>.*)\} (?P<value>\S+)(?: (?P<ts>-?[\d.]+))?$"
)
_TYPES = frozenset({"counter", "gauge", "histogram", "summary", "untyped"})


def _split_exemplar(line: str) -> tuple[str, str | None]:
    """Split a sample line at its OpenMetrics exemplar marker (`` # ``)
    — but only OUTSIDE quoted label values: a client id like
    ``team # 1`` is a legal label value and must stay part of the
    sample (label values are user-controlled; a naive split would
    reject previously-valid exposition)."""
    in_quotes = False
    i = 0
    n = len(line)
    while i < n:
        c = line[i]
        if in_quotes:
            if c == "\\":
                i += 1  # skip the escaped char
            elif c == '"':
                in_quotes = False
        elif c == '"':
            in_quotes = True
        elif c == " " and line[i:i + 3] == " # ":
            return line[:i], line[i + 3:]
        i += 1
    return line, None


def _parse_value(tok: str) -> float | None:
    if tok in ("+Inf", "Inf"):
        return float("inf")
    if tok == "-Inf":
        return float("-inf")
    try:
        return float(tok)
    except ValueError:
        return None


def _parse_labels(raw: str, problems: list, lineno: int) -> tuple | None:
    """``a="x",b="y"`` -> sorted ((name, value), ...) or None on error."""
    out = []
    pos = 0
    raw = raw.strip()
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            problems.append(f"line {lineno}: malformed label at {raw[pos:]!r}")
            return None
        out.append((m.group("name"), m.group("value")))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                problems.append(
                    f"line {lineno}: expected ',' between labels"
                )
                return None
            pos += 1
    names = [n for n, _ in out]
    if len(names) != len(set(names)):
        problems.append(f"line {lineno}: duplicate label name")
        return None
    return tuple(sorted(out))


def parse_exposition(text: str) -> tuple[dict, list[str]]:
    """Strictly parse a Prometheus text exposition (see
    :func:`parse_exposition_full`; this keeps the original two-value
    signature for callers that don't read exemplars)."""
    samples, _exemplars, problems = parse_exposition_full(text)
    return samples, problems


def parse_exposition_full(
    text: str,
) -> tuple[dict, dict, list[str]]:
    """Strictly parse a Prometheus text exposition.

    Returns ``(samples, exemplars, problems)`` — ``samples`` maps
    ``(metric_name, ((label, value), ...))`` to the float value;
    ``exemplars`` maps the same keys to ``{label: value}`` dicts for
    every ``_bucket`` line carrying an OpenMetrics exemplar suffix
    (`` # {trace_id="..."} <value>``).  ``problems`` is empty for a
    conforming exposition; the checks cover what a real scraper
    enforces plus the histogram invariants: TYPE before (and at most
    once per) metric, valid metric/label names, parseable values, no
    duplicate series, cumulative non-decreasing ``_bucket`` counts with
    a ``+Inf`` bucket equal to ``_count``, exemplars only on bucket
    lines with well-formed labels and values, and a trailing newline."""
    problems: list[str] = []
    samples: dict[tuple, float] = {}
    exemplars: dict[tuple, dict] = {}
    typed: dict[str, str] = {}
    seen_sample_of: set[str] = set()
    if text and not text.endswith("\n"):
        problems.append("exposition does not end with a newline")
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3 or not _NAME_RE.fullmatch(parts[2]):
                    problems.append(
                        f"line {lineno}: malformed {parts[1]} comment"
                    )
                    continue
                if parts[1] == "TYPE":
                    name = parts[2]
                    mtype = parts[3].strip() if len(parts) > 3 else ""
                    if mtype not in _TYPES:
                        problems.append(
                            f"line {lineno}: unknown TYPE {mtype!r}"
                        )
                    if name in typed:
                        problems.append(
                            f"line {lineno}: duplicate TYPE for {name}"
                        )
                    if name in seen_sample_of:
                        problems.append(
                            f"line {lineno}: TYPE for {name} after its "
                            "samples"
                        )
                    typed[name] = mtype
            # other comments are allowed and ignored
            continue
        # OpenMetrics exemplar suffix: split it off before the sample
        # grammar match, validate it separately (bucket lines only)
        sample_part, exemplar_raw = _split_exemplar(line)
        m = _SAMPLE_RE.match(sample_part)
        if m is None:
            problems.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        value = _parse_value(m.group("value"))
        if value is None:
            problems.append(
                f"line {lineno}: bad value {m.group('value')!r}"
            )
            continue
        labels = _parse_labels(m.group("labels") or "", problems, lineno)
        if labels is None:
            continue
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        seen_sample_of.add(name)
        seen_sample_of.add(base)
        key = (name, labels)
        if key in samples:
            problems.append(f"line {lineno}: duplicate series {key}")
        samples[key] = value
        if exemplar_raw is not None:
            if not name.endswith("_bucket"):
                problems.append(
                    f"line {lineno}: exemplar on a non-bucket sample "
                    f"{name}"
                )
                continue
            em = _EXEMPLAR_RE.fullmatch(exemplar_raw.strip())
            if em is None:
                problems.append(
                    f"line {lineno}: malformed exemplar "
                    f"{exemplar_raw!r}"
                )
                continue
            ex_labels = _parse_labels(
                em.group("labels") or "", problems, lineno
            )
            if ex_labels is None:
                continue
            if _parse_value(em.group("value")) is None:
                problems.append(
                    f"line {lineno}: bad exemplar value "
                    f"{em.group('value')!r}"
                )
                continue
            exemplars[key] = dict(ex_labels)
    # histogram invariants per (base name, non-le label set)
    for name, mtype in typed.items():
        if mtype != "histogram":
            continue
        series: dict[tuple, list] = {}
        counts: dict[tuple, float] = {}
        for (sname, labels), value in samples.items():
            rest = tuple(kv for kv in labels if kv[0] != "le")
            if sname == f"{name}_bucket":
                le = dict(labels).get("le")
                if le is None:
                    problems.append(f"{name}_bucket missing le label")
                    continue
                series.setdefault(rest, []).append(
                    (_parse_value(le), value)
                )
            elif sname == f"{name}_count":
                counts[labels] = value
        for rest, buckets in series.items():
            buckets.sort(key=lambda b: b[0])
            cum = [v for _, v in buckets]
            if any(b > a for a, b in zip(cum[1:], cum)):
                problems.append(
                    f"{name}{dict(rest)}: bucket counts not cumulative"
                )
            if not buckets or buckets[-1][0] != float("inf"):
                problems.append(f"{name}{dict(rest)}: no +Inf bucket")
            elif counts.get(rest) is not None and (
                buckets[-1][1] != counts[rest]
            ):
                problems.append(
                    f"{name}{dict(rest)}: +Inf bucket != _count"
                )
            if rest not in counts:
                problems.append(f"{name}{dict(rest)}: missing _count")
    return samples, exemplars, problems


def validate_exposition(text: str) -> list[str]:
    """Problems list (empty = conforming); see :func:`parse_exposition`."""
    return parse_exposition(text)[1]
