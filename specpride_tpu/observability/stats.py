"""Run counters, phase timers, structured logging, profiler hook.

Core of the observability subsystem (moved from ``utils/observe.py``,
which remains as a compatibility shim): a structured logger with named
counters (clusters, spectra, peaks, skipped — the categories the
reference prints ad hoc), phase timers covering the pipeline stages
(parse / pack / compute / dispatch / d2h / finalize / write), and an
optional ``jax.profiler`` trace hook for device-level profiling.
"""

from __future__ import annotations

import contextlib
import json
import logging
import sys
import time
from collections import defaultdict

from specpride_tpu.observability import tracing

logger = logging.getLogger("specpride_tpu")


def configure_logging(verbose: int = 0, structured: bool = False) -> None:
    level = logging.WARNING
    if verbose == 1:
        level = logging.INFO
    elif verbose >= 2:
        level = logging.DEBUG
    handler = logging.StreamHandler(sys.stderr)
    if structured:
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    logging.basicConfig(level=level, handlers=[handler], force=True)


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": record.created,
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if extra:
            payload.update(extra)
        return json.dumps(payload)


class RunStats:
    """Counters + phase timers for one pipeline run."""

    def __init__(self) -> None:
        self.counters: dict[str, int] = defaultdict(int)
        self.phases: dict[str, float] = defaultdict(float)
        # set by the pipelined chunk executor (cli._checkpointed_run):
        # {"prefetch", "device_idle_s", "wall_s", "overlap_efficiency"} —
        # carried on the stats object so _finish_run can journal it in
        # run_end without widening every return path
        self.pipeline: dict | None = None
        # set by the robustness harness (specpride_tpu.robustness):
        # retry/degrade/fault accounting journaled in run_end the same
        # way — None whenever the layer stayed dormant
        self.robustness: dict | None = None
        self._start = time.perf_counter()

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    def merge(self, other: "RunStats") -> None:
        """Fold another instance's counters and phase time into this one.

        The pipelined chunk executor gives its packer thread a PRIVATE
        RunStats per chunk and merges it here at handoff, on the consumer
        thread — the ``phases[name] += dt`` read-modify-write is not
        atomic, so two threads must never share one instance."""
        for k, v in other.counters.items():
            self.counters[k] += v
        for k, v in other.phases.items():
            self.phases[k] += v

    @contextlib.contextmanager
    def phase(self, name: str):
        # every phase interval is also a tracing span: the span timeline
        # covers 100% of phase-timer time by construction, so a Chrome
        # trace always accounts for what the phase sums report.  The SPAN
        # wraps the TIMER (not vice versa) so the span's own exit work —
        # the locked journal write — can never make the phase sum exceed
        # the span time; sub-millisecond phases would otherwise flake the
        # >=95%-coverage acceptance check on emit overhead alone.
        with tracing.span(name):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                self.phases[name] += time.perf_counter() - t0

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self._start

    def work_seconds(self) -> float:
        """Summed compute + write phase time — the work actually done this
        run, excluding parse/setup and clusters skipped by a resume."""
        return self.phases.get("compute", 0.0) + self.phases.get("write", 0.0)

    def throughput(self, counter: str = "clusters") -> float:
        """Clusters/sec over the work phases (compute + write).

        Wall time since construction is the wrong denominator: a resumed
        run spends its wall clock on parse + resume-skip filtering and
        would underreport the rate of the clusters it actually computed.
        Falls back to wall time only when no work phase was ever timed."""
        dt = self.work_seconds()
        if dt <= 0.0:
            dt = self.elapsed
        return self.counters[counter] / dt if dt > 0 else 0.0

    def summary(self) -> dict:
        return {
            "elapsed_s": round(self.elapsed, 3),
            "counters": dict(self.counters),
            "phases_s": {k: round(v, 3) for k, v in self.phases.items()},
            # multi-lane executor accounting rides the summary so the
            # serving daemon's terminal response (and its per-lane
            # busy-seconds telemetry) sees it without re-reading journals
            **({"pipeline": self.pipeline} if self.pipeline else {}),
        }

    def log_summary(self) -> None:
        logger.info("run summary", extra={"fields": self.summary()})


@contextlib.contextmanager
def device_trace(trace_dir: str | None):
    """``jax.profiler`` trace hook: active only when a directory is given."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
