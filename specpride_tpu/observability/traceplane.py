"""Cross-process trace reassembly: ``specpride trace --job/--trace-id``.

PRs 1–13 left every process with a private journal on a private
monotonic clock — the submit client, the serving daemon, each served
job, every elastic rank.  This module is the read side of the v4
trace-context plane: given those journal shards and a ``trace_id`` (or
a served ``job_id`` to resolve one), it reassembles ONE causally-linked
Perfetto timeline:

* **clock anchoring** — each journal's ``clock_anchor`` events (paired
  wall<->mono captures with a per-pair ``uncertainty_s``) fit a
  ``wall = mono + offset`` mapping per process run segment, with a
  reported skew bound (max anchor residual from the median offset plus
  the capture uncertainty).  Pre-v4 journals fall back to the envelope
  ``ts``/``mono`` pair of their first event, with a coarse bound.
* **trace extraction** — events belong to the trace when their
  ``trace_id`` matches (run journals stamp every event via
  ``Journal.bind_trace``; the daemon's per-job events carry it
  explicitly) or the id appears in a ``batch_dispatch``'s ``trace_ids``.
  A matching batch additionally pulls in its member jobs' serve spans
  (matched by ``labels.job_id``), so a batch-leader trace spans every
  tenant the shared dispatch served.
* **flow events** — a span whose ``parent_span_id`` resolves to a span
  in a DIFFERENT process track emits a Chrome flow arrow (``ph: s/f``)
  from parent to child, so the client -> daemon -> job -> rank causality
  renders as arrows across tracks, not just stacked slices.
* **critical path** — ``specpride stats --trace ID`` descends the span
  tree from the trace root, at each hop following the child that
  finishes last, and reports each hop's exclusive contribution — the
  chain to shorten first.

Torn shard lines were already dropped deterministically by
``read_events``; journals a trace never touched contribute nothing.
"""

from __future__ import annotations

import os

from specpride_tpu.observability.journal import expand_parts, read_events
from specpride_tpu.observability.tracing import (
    _chrome_process_meta,
    _dump_trace,
)

# fallback skew bound for pre-v4 journals anchored on an envelope
# (ts, mono) pair: the two reads are adjacent but unpaired, so assume a
# generous capture window instead of claiming false precision
_ENVELOPE_ANCHOR_UNCERTAINTY_S = 0.05


def clock_anchor_fit(events: list[dict]) -> tuple[float, float] | None:
    """Fit one process segment's mono axis onto the wall axis.

    Returns ``(offset, bound)`` with ``wall ~ mono + offset`` and every
    anchor within ``bound`` seconds of that line, or None when the
    segment has no usable pair.  The offset is the median over the
    anchors (robust to one NTP step mid-run); the bound is the largest
    residual plus that anchor's own capture uncertainty — the number
    the merger reports as the alignment's worst case."""
    anchors: list[tuple[float, float, float]] = []
    for e in events:
        if e.get("event") != "clock_anchor":
            continue
        mono, wall = e.get("mono"), e.get("wall")
        if isinstance(mono, (int, float)) and isinstance(
            wall, (int, float)
        ):
            anchors.append(
                (mono, wall, float(e.get("uncertainty_s", 0.0)))
            )
    if not anchors:
        for e in events:  # pre-v4: first envelope pair, coarse bound
            mono, ts = e.get("mono"), e.get("ts")
            if isinstance(mono, (int, float)) and isinstance(
                ts, (int, float)
            ):
                anchors.append(
                    (mono, ts, _ENVELOPE_ANCHOR_UNCERTAINTY_S)
                )
                break
    if not anchors:
        return None
    offsets = sorted(w - m for m, w, _ in anchors)
    offset = offsets[len(offsets) // 2]
    bound = max(abs((w - m) - offset) + u for m, w, u in anchors)
    return offset, bound


def _segments(events: list[dict]) -> list[list[dict]]:
    """Split one journal's events at ``run_start`` boundaries — each
    segment is one PROCESS run, so its mono axis is self-consistent
    (a journal reopened across runs must never mix axes in one fit)."""
    segments: list[list[dict]] = []
    for e in events:
        if e.get("event") == "run_start" or not segments:
            segments.append([])
        segments[-1].append(e)
    return segments


def _matches(e: dict, trace_id: str) -> bool:
    if e.get("trace_id") == trace_id:
        return True
    ids = e.get("trace_ids")
    return isinstance(ids, (list, tuple)) and trace_id in ids


def resolve_job_trace(files: list[str], job_id: int) -> str | None:
    """The trace id of served job ``job_id``: from its ``job_done`` /
    ``job_start`` / ``job_queued`` events in any of the journals (last
    writer wins — job ids restart per daemon boot, traces do not)."""
    found = None
    for path in files:
        events, _bad = read_events(path)
        for e in events:
            if e.get("event") in ("job_queued", "job_start", "job_done") \
                    and e.get("job_id") == job_id \
                    and isinstance(e.get("trace_id"), str):
                found = e["trace_id"]
    return found


class TraceView:
    """One reassembled trace: per-shard aligned spans + instants."""

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: list[dict] = []     # wall-aligned, cross-shard
        self.instants: list[dict] = []
        self.shards: list[dict] = []    # {path, pid, offset, bound}
        self.warnings: list[str] = []
        self.violations: list[str] = []

    @property
    def skew_bound_s(self) -> float:
        return max(
            (s["bound"] for s in self.shards if s["bound"] is not None),
            default=0.0,
        )


def _segment_base(path: str) -> str:
    """The logical journal a file belongs to: a rotated segment
    (``serve.jsonl.3``, including a part shard's
    ``x.jsonl.part00000.2``) maps to its un-numbered base — rotation
    splits FILES, not processes, so segments share one event stream,
    one clock fit, and one Chrome process track."""
    root, dot, suffix = path.rpartition(".")
    return root if dot and suffix.isdigit() else path


def extract_trace(journal_paths: list[str], trace_id: str) -> TraceView:
    """Collect one trace's spans and instants from journal shards onto
    one wall axis (one Chrome ``pid`` per logical journal — rotated
    segments of one journal concatenate into its stream)."""
    view = TraceView(trace_id)
    files: list[str] = []
    for p in journal_paths:
        got, warn = expand_parts(p)
        files.extend(got)
        view.warnings.extend(warn)
    # group rotated segments under their logical journal, preserving
    # expand_parts order (segments arrive oldest-first before their
    # live file, so concatenation reconstructs the written stream)
    streams: list[tuple[str, list[dict]]] = []
    by_base: dict[str, list[dict]] = {}
    for path in files:
        events, bad = read_events(path)
        view.violations.extend(bad)
        base = _segment_base(path)
        if base not in by_base:
            by_base[base] = []
            streams.append((base, by_base[base]))
        by_base[base].extend(events)
    pid = 0
    for path, events in streams:
        shard_spans: list[dict] = []
        shard_instants: list[dict] = []
        fit_used: tuple[float, float] | None = None
        for seg in _segments(events):
            fit = clock_anchor_fit(seg)
            # a shared batch dispatch pulls its member jobs' serve
            # spans AND the shared serve:batch span itself into every
            # member's trace: the causal join a single trace_id match
            # cannot see (members and the leader carry their own ids)
            batches = [
                e for e in seg
                if e.get("event") == "batch_dispatch"
                and _matches(e, trace_id)
            ]
            linked_jobs = {
                j for e in batches for j in (e.get("jobs") or ())
            }
            linked_spans = {
                e.get("span_id") for e in batches if e.get("span_id")
            }
            for e in seg:
                linked = (
                    not _matches(e, trace_id)
                    and e.get("event") == "span"
                    and (
                        (e.get("labels") or {}).get("job_id")
                        in linked_jobs and linked_jobs
                        or e.get("span_id") in linked_spans
                    )
                )
                if not (_matches(e, trace_id) or linked):
                    continue
                mono = e.get("mono")
                if fit is None or not isinstance(mono, (int, float)):
                    continue
                wall = mono + fit[0]
                fit_used = fit
                if e.get("event") == "span":
                    dur = float(e.get("dur_s", 0.0))
                    rec = {
                        "name": e["name"],
                        "start": wall - dur,
                        "end": wall,
                        "dur": dur,
                        "pid": pid,
                        "tid": e.get("tid", 0),
                        "span_id": e.get("span_id"),
                        "parent_span_id": e.get("parent_span_id"),
                        "labels": dict(e.get("labels") or {}),
                    }
                    if linked:
                        rec["labels"]["linked"] = "batch"
                    shard_spans.append(rec)
                else:
                    shard_instants.append({
                        "name": e["event"],
                        "wall": wall,
                        "pid": pid,
                        "args": {
                            k: v for k, v in e.items()
                            if k not in ("v", "ts", "mono", "event")
                        },
                    })
        if shard_spans or shard_instants:
            view.shards.append({
                "path": path,
                "pid": pid,
                "offset": fit_used[0] if fit_used else None,
                "bound": fit_used[1] if fit_used else None,
            })
            view.spans.extend(shard_spans)
            view.instants.extend(shard_instants)
            pid += 1
    return view


def _flow_events(view: TraceView) -> list[dict]:
    """Chrome flow arrows for every cross-process parent -> child edge.

    The arrow starts inside the parent slice and finishes at the child
    slice's start; the flow id is the child's span id (unique per
    edge).  Same-process edges stay implicit — slice nesting already
    shows them."""
    by_id = {
        s["span_id"]: s for s in view.spans if s.get("span_id")
    }
    flows: list[dict] = []
    for child in view.spans:
        parent = by_id.get(child.get("parent_span_id"))
        if parent is None or parent["pid"] == child["pid"]:
            continue
        fid = child["span_id"]
        # the source timestamp must land inside the parent slice
        src_ts = min(max(child["start"], parent["start"]), parent["end"])
        flows.append({
            "name": "causal", "cat": "flow", "ph": "s", "id": fid,
            "ts": src_ts * 1e6, "pid": parent["pid"],
            "tid": parent["tid"],
        })
        flows.append({
            "name": "causal", "cat": "flow", "ph": "f", "bp": "e",
            "id": fid, "ts": child["start"] * 1e6,
            "pid": child["pid"], "tid": child["tid"],
        })
    return flows


def build_trace_chrome(
    journal_paths: list[str], trace_id: str, out_path: str
) -> TraceView:
    """Write the reassembled trace as Perfetto-loadable trace-event
    JSON: one process track per shard (named by file), complete spans,
    instant markers, and cross-process flow arrows.  Returns the view
    (span/track counts, skew bound, violations) for the caller to
    report; writes nothing when the trace has no spans at all."""
    view = extract_trace(journal_paths, trace_id)
    if not view.spans and not view.instants:
        return view
    events: list[dict] = []
    for shard in view.shards:
        events.append(_chrome_process_meta(
            shard["pid"], os.path.basename(shard["path"]),
        ))
    for s in view.spans:
        events.append({
            "name": s["name"], "cat": "span", "ph": "X",
            "ts": s["start"] * 1e6, "dur": s["dur"] * 1e6,
            "pid": s["pid"], "tid": s["tid"],
            "args": {
                **s["labels"],
                **({"span_id": s["span_id"]} if s["span_id"] else {}),
            },
        })
    for i in view.instants:
        events.append({
            "name": i["name"], "cat": "event", "ph": "i", "s": "t",
            "ts": i["wall"] * 1e6, "pid": i["pid"], "tid": 0,
            "args": i["args"],
        })
    events.extend(_flow_events(view))
    _dump_trace(events, out_path)
    return view


# -- critical path -------------------------------------------------------


def critical_path(view: TraceView) -> list[dict]:
    """The chain to shorten first: descend from the trace root span, at
    each level into the child that finishes LAST, until a leaf.  Each
    hop reports its exclusive contribution — its duration minus the
    picked child's — so the rows sum (approximately) to the trace's
    end-to-end wall."""
    spans = [s for s in view.spans if s.get("span_id")]
    if not spans:
        return []
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        p = s.get("parent_span_id")
        if p in by_id:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    # the root interval: earliest-starting top-level span (ties: the
    # longest) — with several orphan roots (e.g. rank-local roots whose
    # parent span was never journaled), walk the one that starts first
    cur = min(roots, key=lambda s: (s["start"], -s["end"]))
    path: list[dict] = []
    while cur is not None:
        kids = children.get(cur["span_id"], [])
        nxt = max(kids, key=lambda s: s["end"]) if kids else None
        contrib = cur["dur"] - (nxt["dur"] if nxt is not None else 0.0)
        path.append({
            "name": cur["name"],
            "pid": cur["pid"],
            "start": cur["start"],
            "dur_s": round(cur["dur"], 6),
            "self_s": round(max(contrib, 0.0), 6),
            "labels": cur.get("labels") or {},
        })
        cur = nxt
    return path


def render_critical_path(view: TraceView, out) -> None:
    """The ``specpride stats --trace ID`` rendering."""
    path = critical_path(view)
    if not path:
        print(
            f"trace {view.trace_id}: no spans with causal ids found "
            "(v4 journals emit them when a trace context is installed)",
            file=out,
        )
        return
    total = max(s["end"] for s in view.spans) - min(
        s["start"] for s in view.spans
    )
    print(
        f"trace {view.trace_id}: {len(view.spans)} span(s) across "
        f"{len(view.shards)} process(es), wall {total:.3f}s, "
        f"clock-skew bound {view.skew_bound_s:.4f}s", file=out,
    )
    print("critical path (exclusive seconds per hop):", file=out)
    for i, hop in enumerate(path):
        extras = "".join(
            f" {k}={v}" for k, v in sorted(hop["labels"].items())
            if k in ("job_id", "kernel", "chunk_index", "rank")
        )
        print(
            f"  {'  ' * min(i, 8)}{hop['name']} [pid {hop['pid']}] "
            f"self={hop['self_s']:.3f}s total={hop['dur_s']:.3f}s"
            f"{extras}", file=out,
        )
