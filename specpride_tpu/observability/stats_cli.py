"""``specpride stats``: read one or more run journals and render a human
summary plus a machine-readable aggregate.

Accepts base journal paths (multi-host ``.part<id>`` shards resolve
rank-aware like ``merge-parts``) or explicit files.  Exits non-zero on
schema violations — CI runs this over a pipeline invocation's journal,
so a silently drifting event schema fails the build instead of rotting.

``--top-spans N`` additionally renders the N slowest tracing spans
(self time, count, p50/p99) from the journals' v2 ``span`` events, so a
perf regression is diagnosable without opening a trace UI.
"""

from __future__ import annotations

import json
import sys

from specpride_tpu.observability.journal import (
    expand_parts,
    expand_segments,
    read_events,
    validate_event,
)
from specpride_tpu.observability.tracing import (
    aggregate_spans,
    render_top_spans,
)


def _split_runs(events: list[dict]) -> list[list[dict]]:
    """Split one journal's events into per-run segments at ``run_start``
    boundaries.  Journals open in append mode, so a crashed run resumed
    with the same ``--journal`` path holds several runs back to back —
    summarizing them as one would pair run 1's heartbeats with run 2's
    ``run_end``."""
    segments: list[list[dict]] = []
    for e in events:
        if e["event"] == "run_start" or not segments:
            segments.append([])
        segments[-1].append(e)
    return segments


def _summarize_run(path: str, events: list[dict]) -> dict:
    start = next((e for e in events if e["event"] == "run_start"), None)
    end = next(
        (e for e in reversed(events) if e["event"] == "run_end"), None
    )
    chunks = [e for e in events if e["event"] == "chunk_done"]
    compiles = sum(1 for e in events if e["event"] == "compile")
    dispatches = sum(1 for e in events if e["event"] == "dispatch")
    resumes = sum(1 for e in events if e["event"] == "resume")
    skipped = sum(
        len(e.get("cluster_ids", ()))
        for e in events
        if e["event"] == "skipped_clusters"
    )
    run: dict = {
        "journal": path,
        "n_events": len(events),
        "complete": end is not None,
        "resumes": resumes,
        "chunks": len(chunks),
        "skipped_clusters": skipped,
    }
    # robustness layer: injected-fault / recovery accounting (absent on
    # runs where the layer stayed dormant, so old journals render as
    # before).  The pairing audit runs here — an unrecovered fault in a
    # "green" journal is exactly the silent rot `specpride stats` exists
    # to surface.
    rb_counts = {
        kind: sum(1 for e in events if e["event"] == kind)
        for kind in (
            "fault", "retry", "degrade", "quarantine", "resume_repair",
            "watchdog_stall",
        )
    }
    if any(rb_counts.values()) or (end or {}).get("robustness"):
        from specpride_tpu.robustness.faults import audit_fault_recovery

        rb: dict = {k: v for k, v in rb_counts.items() if v}
        rb["unrecovered_faults"] = len(audit_fault_recovery(events))
        if end and end.get("robustness"):
            rb["run_end"] = end["robustness"]
        run["robustness"] = rb
    # elastic multi-host: the rank's own run_end summary (ranges run /
    # committed, expiries + reassignments it observed); the cross-rank
    # fleet view renders separately from the merged journals
    el = (end or {}).get("elastic")
    if el:
        run["elastic"] = el
    # warm-start subsystem: AOT warmup outcomes + persistent-compile-
    # cache accounting (absent on runs that predate the subsystem or
    # never touched a device backend)
    warmups = [e for e in events if e["event"] == "warmup"]
    cache_ev = next(
        (e for e in events if e["event"] == "compile_cache"), None
    )
    cc = (end or {}).get("compile_cache")
    if warmups or cache_ev or cc:
        ws: dict = {}
        if warmups:
            ws["kernels_warmed"] = len(warmups)
            ws["warmup_cache_hits"] = sum(
                1 for e in warmups if e.get("cache_hit")
            )
            ws["warmup_s"] = round(
                sum(e.get("seconds", 0.0) for e in warmups), 4
            )
        if cache_ev:
            ws["cache_dir"] = (
                cache_ev.get("dir") if cache_ev.get("enabled")
                else f"off ({cache_ev.get('reason')})"
            )
        if cc:
            # fresh XLA compiles this run vs persistent-cache loads
            ws["fresh_compiles"] = cc.get("misses", 0)
            ws["cache_hits"] = cc.get("hits", 0)
            ws["compile_s_saved"] = cc.get("saved_s", 0.0)
        # per-run snapshot-and-diff deltas of the process-wide
        # singletons (meaningful in multi-job serving processes):
        # bucket-plan-cache traffic and first-dispatch shape classes
        pc = (end or {}).get("plan_cache")
        if pc:
            ws["plan_cache_hits"] = pc.get("hits", 0)
            ws["plan_cache_misses"] = pc.get("misses", 0)
        sc = (end or {}).get("shape_classes")
        if sc:
            ws["new_shape_classes"] = sc.get("new", 0)
        run["warmstart"] = ws
    # serving daemon journal (command == "serve"): per-job telemetry
    # rolled up into the operator's at-a-glance serving summary
    serve_ev = next(
        (e for e in events if e["event"] == "serve_start"), None
    )
    jobs = [e for e in events if e["event"] == "job_done"]
    rejected = [e for e in events if e["event"] == "job_rejected"]
    if serve_ev or jobs or rejected:
        sv: dict = {
            "jobs_done": sum(1 for e in jobs if e.get("status") == "done"),
            "jobs_failed": sum(
                1 for e in jobs if e.get("status") != "done"
            ),
            "jobs_rejected": len(rejected),
        }
        if serve_ev:
            sv["socket"] = serve_ev.get("socket")
            sv["warmed_kernels"] = serve_ev.get("warmed_kernels", 0)
            if serve_ev.get("workers") is not None:
                sv["n_workers"] = serve_ev.get("workers")
        # worker-pool attribution: job_done events from a multi-lane
        # daemon carry a `worker` field — group them so interleaved
        # journals from concurrent lanes stay auditable per lane
        workers: dict[str, dict] = {}
        for e in jobs:
            w = e.get("worker")
            if w is None:
                continue
            row = workers.setdefault(
                str(w), {"jobs": 0, "failed": 0, "busy_s": 0.0}
            )
            row["jobs"] += 1
            if e.get("status") != "done":
                row["failed"] += 1
            if isinstance(e.get("wall_s"), (int, float)):
                row["busy_s"] = round(row["busy_s"] + e["wall_s"], 4)
        if workers:
            sv["workers"] = workers
        walls = [e["wall_s"] for e in jobs]
        if walls:
            sv["mean_wall_s"] = round(sum(walls) / len(walls), 4)
            sv["max_wall_s"] = round(max(walls), 4)
        waits = [
            e["queue_wait_s"] for e in jobs
            if isinstance(e.get("queue_wait_s"), (int, float))
        ]
        if waits:
            sv["max_queue_wait_s"] = round(max(waits), 4)
        # warm jobs: requests that journaled ZERO fresh XLA compiles —
        # the serving acceptance number (steady state should be 100%)
        fresh = [
            e["fresh_compiles"] for e in jobs
            if isinstance(e.get("fresh_compiles"), int)
        ]
        if fresh:
            sv["warm_jobs"] = sum(1 for f in fresh if f == 0)
        # SLO accounting (daemons booted with --slo): job_done carries
        # the per-job evaluation — aggregate per method into objective /
        # jobs / breaches / burn, the view `stats --slo` renders
        slo_jobs = [e for e in jobs if "slo_objective_s" in e]
        if slo_jobs:
            slo: dict = {}
            for e in slo_jobs:
                m = str(e.get("method") or "-")
                row = slo.setdefault(m, {
                    "objective_s": e["slo_objective_s"], "jobs": 0,
                    "breaches": 0, "max_latency_s": 0.0,
                })
                row["objective_s"] = e["slo_objective_s"]
                row["jobs"] += 1
                if not e.get("slo_ok", True):
                    row["breaches"] += 1
                lat = e.get("slo_latency_s")
                if isinstance(lat, (int, float)):
                    row["max_latency_s"] = max(row["max_latency_s"], lat)
            for row in slo.values():
                row["burn_frac"] = round(row["breaches"] / row["jobs"], 4)
                row["max_latency_s"] = round(row["max_latency_s"], 4)
            sv["slo"] = slo
            sv["slo_breaches"] = sum(r["breaches"] for r in slo.values())
        # cross-job micro-batching: shared-dispatch rollup from the
        # batch_dispatch events (jobs coalesced, merged clusters, bucket
        # occupancy, window wait) — the journal-side view of the
        # specpride_serve_batch_* exposition
        shared_b = [
            e for e in events
            if e["event"] == "batch_dispatch" and e.get("status") == "shared"
        ]
        fellback = sum(
            1 for e in events
            if e["event"] == "batch_dispatch"
            and e.get("status") == "fallback_solo"
        )
        if shared_b or fellback:
            bt: dict = {
                "dispatches": len(shared_b),
                "batched_jobs": sum(e.get("n_jobs", 0) for e in shared_b),
                "clusters": sum(e.get("n_clusters", 0) for e in shared_b),
            }
            if shared_b:
                bt["max_jobs"] = max(e.get("n_jobs", 0) for e in shared_b)
                bt["mean_occupancy"] = round(
                    sum(e.get("bucket_occupancy_frac", 0.0)
                        for e in shared_b) / len(shared_b), 4,
                )
                bt["mean_window_wait_s"] = round(
                    sum(e.get("window_wait_s", 0.0) for e in shared_b)
                    / len(shared_b), 4,
                )
                bt["fresh_compiles"] = sum(
                    e.get("fresh_compiles", 0) for e in shared_b
                )
            if fellback:
                bt["fallback_solo"] = fellback
            sv["batching"] = bt
        monos = [
            e["mono"] for e in jobs if isinstance(e.get("mono"), (int, float))
        ]
        anchor = (
            serve_ev.get("mono") if serve_ev else (min(monos) if monos else 0)
        )
        if jobs and isinstance(anchor, (int, float)):
            span = max(monos) - anchor if monos else 0.0
            if span > 0:
                sv["jobs_per_sec"] = round(len(jobs) / span, 3)
        run["serving"] = sv
    # closed-loop controller (--autotune): decision rollup + the full
    # evidence-bearing log (rendered per-decision by `stats --autotune`,
    # audited offline by `specpride autotune-replay`)
    at_events = [e for e in events if e["event"] == "autotune"]
    if at_events:
        knobs: dict[str, dict] = {}
        for e in at_events:
            k = str(e.get("knob"))
            row = knobs.setdefault(k, {"decisions": 0, "acted": 0})
            row["decisions"] += 1
            if e.get("acted"):
                row["acted"] += 1
                row["value"] = e.get("new")
        run["autotune"] = {
            "mode": at_events[-1].get("mode"),
            "decisions": len(at_events),
            "acted": sum(1 for e in at_events if e.get("acted")),
            "knobs": knobs,
            "log": [
                {
                    "knob": e.get("knob"), "old": e.get("old"),
                    "new": e.get("new"), "acted": bool(e.get("acted")),
                    "reason": e.get("reason"),
                    "clock": e.get("clock"),
                }
                for e in at_events
            ],
        }
    # content-addressed result cache (v7): the per-run accounting
    # record emitted just before run_end — rendered as the
    # `result-cache:` line (hit rate derived here so --json carries it)
    rc_ev = next(
        (e for e in reversed(events) if e["event"] == "result_cache"),
        None,
    )
    if rc_ev is not None:
        hits = int(rc_ev.get("hits") or 0)
        misses = int(rc_ev.get("misses") or 0)
        consulted = hits + misses
        rc: dict = {
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / consulted, 4) if consulted else 0.0,
            "populated": int(rc_ev.get("populated") or 0),
            "evictions": int(rc_ev.get("evictions") or 0),
            "bytes_saved": int(rc_ev.get("bytes_saved") or 0),
        }
        for opt in ("shared_hits", "corrupt", "entries", "bytes"):
            if rc_ev.get(opt) is not None:
                rc[opt] = rc_ev[opt]
        run["result_cache"] = rc
    # flight recorder (--flightrec): incident rollup + the full log
    # (rendered per-incident by `stats --incidents`, audited offline by
    # `specpride incident-replay`)
    inc_events = [e for e in events if e["event"] == "incident"]
    if inc_events:
        by_det: dict[str, dict] = {}
        for e in inc_events:
            d = str(e.get("detector"))
            row = by_det.setdefault(
                d, {"incidents": 0, "bundled": 0, "suppressed": 0}
            )
            row["incidents"] += 1
            if e.get("bundled"):
                row["bundled"] += 1
            row["suppressed"] += int(e.get("suppressed") or 0)
        run["incidents"] = {
            "mode": inc_events[-1].get("mode"),
            "incidents": len(inc_events),
            "bundled": sum(1 for e in inc_events if e.get("bundled")),
            "suppressed": sum(
                int(e.get("suppressed") or 0) for e in inc_events
            ),
            "detectors": by_det,
            "log": [
                {
                    "detector": e.get("detector"),
                    "incident_id": e.get("incident_id"),
                    "clock": e.get("clock"),
                    "reason": e.get("reason"),
                    "bundled": bool(e.get("bundled")),
                    "suppressed": int(e.get("suppressed") or 0),
                    **({"bundle_dir": e["bundle_dir"]}
                       if e.get("bundle_dir") else {}),
                }
                for e in inc_events
            ],
        }
    if start:
        run.update(
            command=start.get("command"),
            method=start.get("method"),
            backend=start.get("backend"),
            n_clusters=start.get("n_clusters"),
        )
    if chunks:
        rates = [c["clusters_per_sec"] for c in chunks]
        run["mean_chunk_clusters_per_sec"] = round(
            sum(rates) / len(rates), 2
        )
    if end:
        device = end.get("device", {})
        run.update(
            counters=end.get("counters", {}),
            phases_s=end.get("phases_s", {}),
            elapsed_s=end.get("elapsed_s"),
            representatives_written=end.get("representatives_written"),
            compile_count=max(compiles, device.get("compiles", 0)),
            dispatch_count=max(dispatches, device.get("dispatches", 0)),
            padding_waste_frac=device.get("padding_waste_frac", 0.0),
            bucket_occupancy_frac=device.get("bucket_occupancy_frac", 0.0),
            bytes_h2d=device.get("bytes_h2d", 0),
            bytes_d2h=device.get("bytes_d2h", 0),
            device_peak_bytes_in_use=device.get(
                "device_peak_bytes_in_use", 0
            ),
        )
        # derived transfer bandwidth: per-run H2D/D2H byte totals over
        # the run wall (PR1 journaled the bytes; never rendered as a
        # rate until the memory-bandwidth campaign made it the headline)
        elapsed = end.get("elapsed_s") or 0
        if elapsed and (run["bytes_h2d"] or run["bytes_d2h"]):
            mb = 1024.0 * 1024.0
            run["bandwidth"] = {
                "h2d_mb": round(run["bytes_h2d"] / mb, 3),
                "d2h_mb": round(run["bytes_d2h"] / mb, 3),
                "h2d_mb_per_s": round(run["bytes_h2d"] / mb / elapsed, 3),
                "d2h_mb_per_s": round(run["bytes_d2h"] / mb / elapsed, 3),
            }
        prec = end.get("precision")
        if prec:
            run["precision"] = prec
        pipeline = end.get("pipeline")
        if pipeline:
            # multi-lane chunk executor (--prefetch / --pack-workers /
            # --async-write): dispatch-lane starvation, per-lane busy
            # seconds, and reorder-buffer head-of-line stall time
            run["prefetch"] = pipeline.get("prefetch")
            run["device_idle_s"] = pipeline.get("device_idle_s")
            run["overlap_efficiency"] = pipeline.get("overlap_efficiency")
            for key in (
                "pack_workers", "async_write", "wall_s", "pack_busy_s",
                "write_busy_s", "reorder_stall_s", "h2d",
            ):
                if pipeline.get(key) is not None:
                    run[key] = pipeline[key]
    else:
        # dead run: the heartbeats are all we have — surface the last one
        run["compile_count"] = compiles
        run["dispatch_count"] = dispatches
        if chunks:
            run["last_chunk"] = chunks[-1]
    return run


def _render_serving(sv: dict, out) -> None:
    """The serving daemon's at-a-glance line: job outcomes, warm-request
    count (jobs with zero fresh compiles), latency and queue pressure."""
    bits = [
        f"jobs_done={sv.get('jobs_done', 0)}",
        f"failed={sv.get('jobs_failed', 0)}",
        f"rejected={sv.get('jobs_rejected', 0)}",
    ]
    if "warm_jobs" in sv:
        bits.append(f"warm={sv['warm_jobs']}")
    if "mean_wall_s" in sv:
        bits.append(f"mean_wall_s={sv['mean_wall_s']}")
    if "max_queue_wait_s" in sv:
        bits.append(f"max_queue_wait_s={sv['max_queue_wait_s']}")
    if "jobs_per_sec" in sv:
        bits.append(f"jobs_per_sec={sv['jobs_per_sec']}")
    if "warmed_kernels" in sv:
        bits.append(f"warmed_kernels={sv['warmed_kernels']}")
    if "slo_breaches" in sv:
        bits.append(f"slo_breaches={sv['slo_breaches']}")
    if "n_workers" in sv:
        bits.append(f"workers={sv['n_workers']}")
    print(f"  serving: {' '.join(bits)}", file=out)
    # cross-job micro-batching rollup (daemons booted with
    # --batch-window): how much work rode shared dispatches
    bt = sv.get("batching")
    if bt:
        bbits = [
            f"dispatches={bt.get('dispatches', 0)}",
            f"jobs={bt.get('batched_jobs', 0)}",
            f"clusters={bt.get('clusters', 0)}",
        ]
        if "max_jobs" in bt:
            bbits.append(f"max_jobs={bt['max_jobs']}")
        if "mean_occupancy" in bt:
            bbits.append(f"mean_occupancy={bt['mean_occupancy']}")
        if "mean_window_wait_s" in bt:
            bbits.append(
                f"mean_window_wait_s={bt['mean_window_wait_s']}"
            )
        if "fresh_compiles" in bt:
            bbits.append(f"fresh_compiles={bt['fresh_compiles']}")
        if bt.get("fallback_solo"):
            bbits.append(f"fallback_solo={bt['fallback_solo']}")
        print(f"  batching: {' '.join(bbits)}", file=out)
    # per-lane rollup (multi-worker daemons): which lane ran what, and
    # how busy it was — the journal-side view of the exporter's
    # specpride_serve_worker_busy_seconds_total{worker}
    workers = sv.get("workers") or {}
    for w in sorted(workers, key=lambda k: (len(k), k)):
        row = workers[w]
        failed = f" failed={row['failed']}" if row.get("failed") else ""
        print(
            f"    worker {w}: jobs={row['jobs']}{failed} "
            f"busy_s={row['busy_s']}", file=out,
        )


def _render_autotune(run: dict, out, detail: bool = False) -> None:
    """The controller's at-a-glance line from the journal's `autotune`
    events; ``stats --autotune`` adds the per-decision log (knob,
    old -> new, acted, reason) — the human view of the evidence
    `specpride autotune-replay` audits."""
    at = run.get("autotune")
    if not at:
        if detail:
            print(
                "  autotune: no decisions in this journal (was the run "
                "booted with --autotune observe|on?)", file=out,
            )
        return
    per_knob = " ".join(
        f"{k}={row['value']}"
        for k, row in sorted(at.get("knobs", {}).items())
        if "value" in row
    )
    print(
        f"  autotune: mode={at.get('mode')} "
        f"decisions={at.get('decisions', 0)} "
        f"acted={at.get('acted', 0)}"
        + (f" {per_knob}" if per_knob else ""), file=out,
    )
    if detail:
        for d in at.get("log", ()):
            mark = "acted" if d.get("acted") else "observed"
            print(
                f"    {d.get('knob')}: {d.get('old')} -> {d.get('new')} "
                f"[{mark}] {d.get('reason')}", file=out,
            )


def _render_incidents(run: dict, out, detail: bool = False) -> None:
    """The flight recorder's at-a-glance line from the journal's v6
    `incident` events; ``stats --incidents`` adds the per-incident log
    (detector, clock, reason, bundle) — the human view of the evidence
    `specpride incident-replay` audits."""
    inc = run.get("incidents")
    if not inc:
        if detail:
            print(
                "  incidents: none in this journal (was the run booted "
                "with --flightrec observe|on?)", file=out,
            )
        return
    per_det = " ".join(
        f"{d}={row['incidents']}"
        for d, row in sorted(inc.get("detectors", {}).items())
    )
    print(
        f"  incidents: mode={inc.get('mode')} "
        f"total={inc.get('incidents', 0)} "
        f"bundled={inc.get('bundled', 0)} "
        f"suppressed={inc.get('suppressed', 0)}"
        + (f" {per_det}" if per_det else ""), file=out,
    )
    if detail:
        for i in inc.get("log", ()):
            mark = "bundled" if i.get("bundled") else "observed"
            sup = (
                f" (+{i['suppressed']} suppressed)"
                if i.get("suppressed") else ""
            )
            where = (
                f" -> {i['bundle_dir']}" if i.get("bundle_dir") else ""
            )
            print(
                f"    {i.get('incident_id')} {i.get('detector')} "
                f"@ {i.get('clock')}: {i.get('reason')} "
                f"[{mark}]{sup}{where}", file=out,
            )


def _render_result_cache(run: dict, out) -> None:
    """The result cache's at-a-glance line from the journal's v7
    `result_cache` event: how much consensus work the run did NOT
    redo, and what the local tier's LRU had to give up for it."""
    rc = run.get("result_cache")
    if not rc:
        return
    bits = [
        f"hits={rc.get('hits', 0)}",
        f"misses={rc.get('misses', 0)}",
        f"hit_rate={rc.get('hit_rate', 0.0):.1%}",
        f"evictions={rc.get('evictions', 0)}",
        f"bytes_saved={rc.get('bytes_saved', 0)}",
    ]
    if rc.get("shared_hits"):
        bits.append(f"shared_hits={rc['shared_hits']}")
    if rc.get("corrupt"):
        bits.append(f"corrupt={rc['corrupt']}")
    if rc.get("entries") is not None:
        bits.append(f"entries={rc['entries']}")
    print(f"  result-cache: {' '.join(bits)}", file=out)


def _render_slo(run: dict, out) -> None:
    """``stats --slo``: the per-method SLO table from a serving
    journal's job_done evaluations (objective vs measured queue-wait +
    wall latency, breach count, burn fraction)."""
    sv = run.get("serving") or {}
    slo = sv.get("slo")
    if not slo:
        print(
            "  slo: no SLO-evaluated jobs in this journal (was the "
            "daemon booted with --slo?)", file=out,
        )
        return
    for method in sorted(slo):
        row = slo[method]
        print(
            f"  slo: method={method} objective_s={row['objective_s']} "
            f"jobs={row['jobs']} breaches={row['breaches']} "
            f"burn={row['burn_frac']:.1%} "
            f"max_latency_s={row['max_latency_s']}", file=out,
        )


def _render_rank_view(view: dict, out) -> None:
    """The multi-host rank view (``parallel.elastic.summarize_ranks``):
    one line per rank from the merged ``.part<rank>`` journals, plus the
    lease-expiry/reassignment pairing audit."""
    audit = view.get("unpaired_lease_expiries", 0)
    state = "UNPAIRED" if audit else "unpaired"
    print(
        f"ranks: {len(view.get('ranks', {}))} seen, "
        f"{view.get('reassignments', 0)} reassignment(s), "
        f"{view.get('lease_splits', 0)} split(s), "
        f"{audit} {state} lease expiries/splits", file=out,
    )
    for rank, r in view.get("ranks", {}).items():
        age = r.get("last_heartbeat_age_s")
        bits = [
            f"last_heartbeat_age_s={age if age is not None else '-'}",
            f"chunks={r.get('chunks_committed', 0)}",
            f"ranges={r.get('ranges_claimed', 0)}",
        ]
        if r.get("takeovers"):
            bits.append(f"takeovers={r['takeovers']}")
        if r.get("leases_expired"):
            bits.append(f"leases_expired={r['leases_expired']}")
        if r.get("reassigned_away"):
            bits.append(f"reassigned_away={r['reassigned_away']}")
        if r.get("lease_splits"):
            bits.append(f"lease_splits={r['lease_splits']}")
        if r.get("steals"):
            bits.append(f"steals={r['steals']}")
        # stale-but-alive: heartbeat silent past the TTL with leases
        # still held and no expiry recorded — the rank the fleet should
        # be stealing from (or the autoscaler replacing)
        slow = "slow: " if r.get("slow") else ""
        print(f"  rank {rank}: {slow}{' '.join(bits)}", file=out)


def _render_run(run: dict, out, slo: bool = False,
                autotune: bool = False, incidents: bool = False) -> None:
    head = (
        f"{run['journal']}: {run.get('command', '?')}"
        f"/{run.get('method', '?')} backend={run.get('backend', '?')}"
    )
    print(head, file=out)
    if not run["complete"]:
        live = run.get("serving")
        print(
            "  INCOMPLETE — no run_end event ("
            + ("live daemon or crashed" if live else "crashed or still "
               "running")
            + f"); {run['chunks']} chunk(s) journaled", file=out,
        )
        if "last_chunk" in run:
            lc = run["last_chunk"]
            print(
                f"  last heartbeat: chunk {lc['chunk_index']} "
                f"({lc['n_clusters']} clusters, "
                f"{lc['clusters_per_sec']:.1f} cl/s)", file=out,
            )
        if live:
            _render_serving(live, out)
            if slo:
                _render_slo(run, out)
        _render_autotune(run, out, detail=autotune)
        _render_incidents(run, out, detail=incidents)
        _render_result_cache(run, out)
        return
    counters = run.get("counters", {})
    print(
        f"  clusters={counters.get('clusters', 0)} "
        f"representatives={run.get('representatives_written') or 0} "
        f"elapsed={run.get('elapsed_s', 0):.3f}s "
        f"chunks={run['chunks']} resumes={run['resumes']} "
        f"skipped={run['skipped_clusters']}", file=out,
    )
    phases = run.get("phases_s", {})
    if phases:
        print(
            "  phases: "
            + " ".join(f"{k}={v:.3f}s" for k, v in sorted(phases.items())),
            file=out,
        )
    if run.get("device_idle_s") is not None:
        # lane fields only exist in multi-lane-era journals; PR3-era
        # pipeline summaries must render without literal None noise
        lane_bits = "".join(
            f" {key}={run[key]}"
            for key in ("pack_workers", "async_write")
            if run.get(key) is not None
        )
        print(
            f"  pipeline: prefetch={run.get('prefetch')}{lane_bits} "
            f"device_idle_s={run['device_idle_s']:.3f} "
            f"overlap_efficiency={run.get('overlap_efficiency')}", file=out,
        )
        if run.get("pack_busy_s") is not None:
            wall = run.get("wall_s") or 0.0
            busy = run["pack_busy_s"]
            pack = ",".join(f"{b:.3f}" for b in busy) if busy else "-"
            frac = (
                f" ({sum(busy) / (wall * max(len(busy), 1)):.0%} busy)"
                if wall > 0 and busy else ""
            )
            print(
                f"  lanes: pack_busy_s=[{pack}]{frac} "
                f"write_busy_s={run.get('write_busy_s', 0.0):.3f} "
                f"reorder_stall_s={run.get('reorder_stall_s', 0.0):.3f}",
                file=out,
            )
    if run.get("serving"):
        _render_serving(run["serving"], out)
        if slo:
            _render_slo(run, out)
    _render_autotune(run, out, detail=autotune)
    _render_incidents(run, out, detail=incidents)
    _render_result_cache(run, out)
    ws = run.get("warmstart")
    if ws:
        bits = []
        if "kernels_warmed" in ws:
            bits.append(
                f"kernels_warmed={ws['kernels_warmed']} "
                f"warmup_cache_hits={ws['warmup_cache_hits']} "
                f"warmup_s={ws['warmup_s']}"
            )
        if "fresh_compiles" in ws:
            bits.append(
                f"fresh_compiles={ws['fresh_compiles']} "
                f"cache_hits={ws['cache_hits']} "
                f"compile_s_saved={ws['compile_s_saved']}"
            )
        if "plan_cache_hits" in ws:
            bits.append(
                f"plan_cache={ws['plan_cache_hits']}h/"
                f"{ws['plan_cache_misses']}m"
            )
        if "new_shape_classes" in ws:
            bits.append(f"new_shape_classes={ws['new_shape_classes']}")
        if "cache_dir" in ws:
            bits.append(f"cache={ws['cache_dir']}")
        print(f"  warmstart: {' '.join(bits)}", file=out)
    el = run.get("elastic")
    if el:
        extras = "".join(
            f" {key}={el[key]}"
            for key in ("lease_splits", "steals", "cas_conflicts")
            if el.get(key)
        )
        print(
            f"  elastic: rank={el.get('rank')} "
            f"ranges_run={el.get('ranges_run')}/"
            f"{el.get('n_ranges')} "
            f"committed={el.get('ranges_committed')} "
            f"reassignments={el.get('reassignments', 0)}"
            f"{extras}", file=out,
        )
    rb = run.get("robustness")
    if rb:
        bits = " ".join(
            f"{k}={rb[k]}"
            for k in (
                "fault", "retry", "degrade", "quarantine",
                "resume_repair", "watchdog_stall",
            )
            if rb.get(k)
        )
        state = (
            "UNRECOVERED" if rb.get("unrecovered_faults") else "recovered"
        )
        print(
            f"  robustness: {bits or 'armed, no events'} "
            f"({rb.get('unrecovered_faults', 0)} {state})", file=out,
        )
        rend = rb.get("run_end") or {}
        if rend.get("retries") is not None:
            print(
                f"  robustness run_end: retries={rend.get('retries')} "
                f"retry_wait_s={rend.get('retry_wait_s')} "
                f"degrade_splits={rend.get('degrade_splits', 0)} "
                f"degrade_reroutes={rend.get('degrade_reroutes', 0)}",
                file=out,
            )
    print(
        f"  device: compile_count={run['compile_count']} "
        f"dispatches={run['dispatch_count']} "
        f"padding_waste_frac={run['padding_waste_frac']} "
        f"bucket_occupancy_frac={run['bucket_occupancy_frac']} "
        f"h2d={run['bytes_h2d']}B d2h={run['bytes_d2h']}B "
        f"peak_device_mem={run['device_peak_bytes_in_use']}B", file=out,
    )
    bw = run.get("bandwidth")
    if bw:
        bits = [
            f"h2d={bw['h2d_mb']}MB ({bw['h2d_mb_per_s']}MB/s)",
            f"d2h={bw['d2h_mb']}MB ({bw['d2h_mb_per_s']}MB/s)",
        ]
        h2d_lane = run.get("h2d")
        if h2d_lane:
            bits.append(
                f"staged={h2d_lane.get('bytes', 0)}B "
                f"overlap={h2d_lane.get('overlap_efficiency')}"
            )
        print(f"  bandwidth: {' '.join(bits)}", file=out)
    prec = run.get("precision")
    if prec:
        bits = [f"precision={prec.get('precision')}"]
        if prec.get("gated"):
            bits.append(
                f"gate={'ok' if prec.get('ok') else 'FAILED'} "
                f"min_cosine={prec.get('min_cosine')} "
                f"tolerance={prec.get('tolerance')} "
                f"checked={prec.get('checked')}"
            )
        print(f"  precision: {' '.join(bits)}", file=out)


def _read_new_events(path: str, offset: int) -> tuple[list[dict], int]:
    """Complete journal lines past ``offset`` -> (events, new offset).

    Reads only up to the LAST newline, so a line the writer is mid-way
    through never parses torn — it is consumed whole on a later poll.
    A missing file (daemon not booted yet) is simply "nothing new";
    schema-invalid lines are skipped (a live tail must keep rendering,
    the strict exit-nonzero audit belongs to one-shot ``stats``)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(offset)
            blob = fh.read()
    except FileNotFoundError:
        return [], offset
    end = blob.rfind(b"\n")
    if end < 0:
        return [], offset
    chunk = blob[: end + 1]
    events: list[dict] = []
    for line in chunk.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not validate_event(rec):
            events.append(rec)
    return events, offset + len(chunk)


def _poll_rotated(
    path: str, offset: int, segs_seen: int
) -> tuple[list[dict], int, int]:
    """One ``--follow`` poll that survives journal ROTATION
    (``--journal-rotate-mb``): when new numbered segments appeared
    since the last poll, the live file we were tailing was renamed —
    drain the first new segment from our old offset (it IS the old
    live file), later ones whole, then continue on the fresh live file
    from 0.  Returns ``(events, offset, segs_seen)``."""
    rotated = [p for p in expand_segments(path) if p != path]
    events: list[dict] = []
    if len(rotated) > segs_seen:
        for i, seg in enumerate(rotated[segs_seen:]):
            evs, _ = _read_new_events(seg, offset if i == 0 else 0)
            events.extend(evs)
        segs_seen = len(rotated)
        offset = 0
    evs, offset = _read_new_events(path, offset)
    events.extend(evs)
    return events, offset, segs_seen


def follow_stats(
    path: str, out=None, interval: float = 1.0, stop=None,
    max_updates: int = 0, top_spans: int = 0, slo: bool = False,
    autotune: bool = False, incidents: bool = False,
) -> int:
    """``specpride stats --follow``: tail ONE live journal (a serving
    daemon's or a running batch job's) and re-render the summary every
    time new complete events land — an operator watches a daemon
    without restarting ``stats`` per look.

    Renders the LAST run segment in the journal (the live one; a
    journal reopened across runs holds several; a rotating daemon
    journal is followed across its numbered segments).  ``stop`` (a
    ``threading.Event``) and ``max_updates`` are programmatic exits for
    tests; interactively Ctrl-C exits 0."""
    import time as _time

    out = out or sys.stdout
    offset = 0
    # rotated segments that predate this follow are HISTORY — start at
    # the live tail, count them consumed
    segs_seen = len([p for p in expand_segments(path) if p != path])
    events: list[dict] = []
    updates = 0
    try:
        while True:
            new_events, offset, segs_seen = _poll_rotated(
                path, offset, segs_seen
            )
            if new_events:
                events.extend(new_events)
                # only the LAST run segment is ever rendered: drop the
                # prefix before the most recent run_start so a days-long
                # daemon tail stays O(current run), not O(uptime)
                for i in range(len(events) - 1, 0, -1):
                    if events[i]["event"] == "run_start":
                        del events[:i]
                        break
                updates += 1
                segments = _split_runs(events) or [[]]
                stamp = _time.strftime("%H:%M:%S")
                print(
                    f"--- {stamp} update {updates}: {len(events)} "
                    f"event(s) ---", file=out,
                )
                _render_run(_summarize_run(path, segments[-1]), out,
                            slo=slo, autotune=autotune,
                            incidents=incidents)
                from specpride_tpu.parallel.elastic import (
                    summarize_ranks,
                )

                view = summarize_ranks([segments[-1]])
                if view is not None:
                    _render_rank_view(view, out)
                if top_spans:
                    render_top_spans(
                        aggregate_spans([events]), top_spans, out
                    )
                try:
                    out.flush()
                except (AttributeError, OSError):
                    pass
            if stop is not None and stop.is_set():
                return 0
            if max_updates and updates >= max_updates:
                return 0
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def run_stats(
    journal_paths: list[str], json_out: str | None = None, out=None,
    top_spans: int = 0, slo: bool = False, autotune: bool = False,
    incidents: bool = False,
) -> int:
    out = out or sys.stdout
    files: list[str] = []
    warnings: list[str] = []
    for p in journal_paths:
        got, warn = expand_parts(p)
        files.extend(got)
        warnings.extend(warn)
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    if not files:
        print("no journal files to read", file=sys.stderr)
        return 1

    runs: list[dict] = []
    violations: list[str] = []
    events_per_file: list[list[dict]] = []
    for path in files:
        events, bad = read_events(path)
        violations.extend(bad)
        events_per_file.append(events)
        segments = _split_runs(events) or [[]]
        for i, seg in enumerate(segments):
            label = path if len(segments) == 1 else f"{path}#run{i}"
            runs.append(_summarize_run(label, seg))

    for run in runs:
        _render_run(run, out, slo=slo, autotune=autotune,
                    incidents=incidents)
    # cross-rank fleet view: elastic liveness/reassignment rollup over
    # ALL the journals read (the per-rank .part shards merge here)
    from specpride_tpu.parallel.elastic import summarize_ranks

    rank_view = summarize_ranks(events_per_file)
    if rank_view is not None:
        _render_rank_view(rank_view, out)
    span_rows = aggregate_spans(events_per_file) if top_spans else []
    if top_spans:
        render_top_spans(span_rows, top_spans, out)
    totals = {
        "n_journals": len(files),
        "n_runs_complete": sum(r["complete"] for r in runs),
        "clusters": sum(
            r.get("counters", {}).get("clusters", 0) for r in runs
        ),
        "representatives_written": sum(
            r.get("representatives_written") or 0 for r in runs
        ),
        "skipped_clusters": sum(r["skipped_clusters"] for r in runs),
        "compile_count": sum(r.get("compile_count", 0) for r in runs),
    }
    if len(runs) > 1:
        print(
            f"TOTAL: {totals['n_journals']} journals, "
            f"{totals['clusters']} clusters, "
            f"{totals['representatives_written']} representatives, "
            f"{totals['compile_count']} compiles", file=out,
        )
    if json_out:
        agg = {"v": 1, "runs": runs, "totals": totals}
        if rank_view is not None:
            agg["elastic"] = rank_view
        if top_spans:
            agg["top_spans"] = span_rows[:top_spans]
        with open(json_out, "w", encoding="utf-8") as fh:
            json.dump(agg, fh, indent=1)
            fh.write("\n")
    if violations:
        for v in violations:
            print(f"schema violation: {v}", file=sys.stderr)
        print(
            f"{len(violations)} schema violation(s)", file=sys.stderr
        )
        return 1
    return 0
