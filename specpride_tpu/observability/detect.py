"""Health detectors: fold the journal stream into incident firings.

The flight recorder (``flightrec.py``) taps its host's journal and
feeds every record through a :class:`DetectorSet`.  Each detector is a
pure, replayable function of the event stream — the same discipline as
the autotune signal fold: no wall-clock reads, no randomness, no state
outside the records — so ``specpride incident-replay`` can refold a
finished journal through the same code and re-derive every firing (and
every dedup suppression) bit-exact.  Every float that lands in an
evidence payload goes through the 6-decimal rounding rule so live and
replayed incidents compare equal through a JSON round-trip.

Detector catalog (all fed by events the system already emits):

==================  ===================================================
detector            fires when
==================  ===================================================
``slo_breach``      ``streak`` consecutive ``job_done`` events broke
                    their latency objective (``slo_ok: false``)
``latency_spike``   a ``job_done`` wall exceeds ``factor`` x the
                    windowed EWMA of recent walls (after ``min_jobs``
                    observations seeded the estimate)
``queue_sat``       live queue depth (queued-not-started fold) reaches
                    ``frac`` of the admission bound announced by
                    ``serve_start``
``watchdog``        a ``watchdog_stall`` event lands (a lane exceeded
                    its armed timeout)
``retry_exhaust``   a ``retry`` event's attempt count reaches
                    ``attempts`` at one site (the backoff ladder is
                    nearly spent)
``solo_burst``      ``count`` ``batch_dispatch`` events with
                    ``status: fallback_solo`` inside ``window_s`` (the
                    shared dispatch path is failing repeatedly)
``lease_churn``     ``count`` lease lifecycle events (``lease_expire``
                    / ``chunk_reassign`` / ``lease_split``) inside
                    ``window_s`` (ranks dying or thrashing work)
==================  ===================================================

Dedup: one cooldown window per detector, keyed on the TRIGGERING
record's ``mono`` (never the wall clock), so a flapping detector
journals one incident per window with a ``suppressed`` count instead
of bundle-storming — and the suppression decisions replay exactly.
"""

from __future__ import annotations

import collections
import hashlib

# one deterministic parameter set for every construction site (live
# recorder and offline replay build detectors from the same table, so
# they cannot disagree); hosts override per-key via `params`
DEFAULT_PARAMS: dict[str, dict] = {
    "slo_breach": {"streak": 3},
    "latency_spike": {"factor": 4.0, "min_jobs": 8, "alpha": 0.2},
    "queue_sat": {"frac": 0.9},
    "watchdog": {},
    "retry_exhaust": {"attempts": 3},
    "solo_burst": {"count": 3, "window_s": 60.0},
    "lease_churn": {"count": 6, "window_s": 60.0},
    "cooldown_s": 30.0,
}


def _r(x) -> float:
    """The snapshot rounding rule (same as autotune.signals): six
    decimals survives a JSON round-trip exactly, so live and replayed
    evidence payloads compare equal."""
    return round(float(x), 6)


def incident_id(detector: str, clock: float) -> str:
    """Content-derived incident identity: any process refolding the
    same stream mints the same id (the replay bit-parity contract),
    and the id doubles as the bundle directory's name component."""
    key = f"{detector}:{_r(clock):.6f}"
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:16]


def derived_trace_id(detector: str, clock: float) -> str:
    """A 32-hex trace id for an incident whose evidence carried none —
    content-derived so replay reproduces it, and syntactically exactly
    what the v4 trace envelope requires."""
    key = f"incident:{detector}:{_r(clock):.6f}"
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:32]


class _Detector:
    """One pure stream fold.  ``observe(rec, mono)`` mutates state
    deterministically and returns ``(reason, evidence)`` on a firing,
    else None."""

    name = "?"

    def __init__(self, params: dict):
        self.params = params

    def observe(self, rec: dict, mono: float):
        raise NotImplementedError


class SloBreachDetector(_Detector):
    name = "slo_breach"

    def __init__(self, params: dict):
        super().__init__(params)
        self.streak = 0

    def observe(self, rec, mono):
        if rec.get("event") != "job_done":
            return None
        ok = rec.get("slo_ok")
        if ok is True:
            self.streak = 0
            return None
        if ok is not False:
            return None  # no objective covered this job
        self.streak += 1
        need = int(self.params["streak"])
        if self.streak < need:
            return None
        reason = (
            f"{self.streak} consecutive SLO breaches "
            f"(threshold {need})"
        )
        evidence = {
            "streak": self.streak,
            "job_id": rec.get("job_id"),
            "slo_latency_s": _r(rec.get("slo_latency_s") or 0.0),
            "slo_objective_s": _r(rec.get("slo_objective_s") or 0.0),
        }
        return reason, evidence


class LatencySpikeDetector(_Detector):
    name = "latency_spike"

    def __init__(self, params: dict):
        super().__init__(params)
        self.ewma: float | None = None
        self.n = 0

    def observe(self, rec, mono):
        if rec.get("event") != "job_done":
            return None
        wall = rec.get("wall_s")
        if not isinstance(wall, (int, float)):
            return None
        wall = float(wall)
        prev, n = self.ewma, self.n
        alpha = float(self.params["alpha"])
        # fold FIRST (a spike still updates the estimate — one outlier
        # must not keep the baseline stale forever), fire on the
        # estimate as it stood BEFORE this job
        self.ewma = wall if prev is None else (
            prev + alpha * (wall - prev)
        )
        self.n = n + 1
        if prev is None or n < int(self.params["min_jobs"]):
            return None
        factor = float(self.params["factor"])
        if prev <= 0 or wall <= factor * prev:
            return None
        reason = (
            f"job wall {_r(wall)}s is {_r(wall / prev)}x the EWMA "
            f"{_r(prev)}s (threshold {factor}x)"
        )
        evidence = {
            "wall_s": _r(wall),
            "ewma_s": _r(prev),
            "ratio": _r(wall / prev),
            "jobs_seen": n,
            "job_id": rec.get("job_id"),
        }
        return reason, evidence


class QueueSaturationDetector(_Detector):
    name = "queue_sat"

    def __init__(self, params: dict):
        super().__init__(params)
        self.queued = 0
        self.capacity: int | None = None

    def observe(self, rec, mono):
        event = rec.get("event")
        if event == "serve_start":
            cap = rec.get("max_queue")
            if isinstance(cap, int) and cap > 0:
                self.capacity = cap
            return None
        if event == "job_start":
            if self.queued > 0:
                self.queued -= 1
            return None
        if event != "job_queued":
            return None
        self.queued += 1
        if self.capacity is None:
            return None
        frac = float(self.params["frac"])
        if self.queued < frac * self.capacity:
            return None
        reason = (
            f"queue depth {self.queued}/{self.capacity} reached "
            f"{int(frac * 100)}% of the admission bound"
        )
        evidence = {
            "queue_depth": self.queued,
            "max_queue": self.capacity,
            "frac": _r(self.queued / self.capacity),
        }
        return reason, evidence


class WatchdogDetector(_Detector):
    name = "watchdog"

    def observe(self, rec, mono):
        if rec.get("event") != "watchdog_stall":
            return None
        lane = rec.get("lane")
        elapsed = rec.get("elapsed_s")
        reason = f"lane {lane!r} stalled {elapsed}s past its watchdog"
        evidence = {
            "lane": lane,
            "elapsed_s": _r(elapsed or 0.0),
            "timeout_s": _r(rec.get("timeout_s") or 0.0),
        }
        return reason, evidence


class RetryExhaustionDetector(_Detector):
    name = "retry_exhaust"

    def observe(self, rec, mono):
        if rec.get("event") != "retry":
            return None
        attempt = rec.get("attempt")
        if not isinstance(attempt, int):
            return None
        need = int(self.params["attempts"])
        # `attempt` is 0-based: attempt N means N+1 tries are burnt
        if attempt + 1 < need:
            return None
        site = rec.get("site")
        reason = (
            f"retry attempt {attempt + 1} at site {site!r} "
            f"(exhaustion threshold {need})"
        )
        evidence = {
            "site": site,
            "attempt": attempt,
            "backoff_s": _r(rec.get("backoff_s") or 0.0),
        }
        return reason, evidence


class _WindowedBurstDetector(_Detector):
    """Shared shape for count-inside-window detectors: a deque of
    trigger monos, cut at the window bound on every observation."""

    events: frozenset = frozenset()

    def __init__(self, params: dict):
        super().__init__(params)
        self._hits: collections.deque = collections.deque()

    def _match(self, rec) -> bool:
        return rec.get("event") in self.events

    def _fire(self, rec, n: int):
        raise NotImplementedError

    def observe(self, rec, mono):
        if not self._match(rec):
            return None
        window = float(self.params["window_s"])
        self._hits.append(mono)
        cut = mono - window
        while self._hits and self._hits[0] < cut:
            self._hits.popleft()
        n = len(self._hits)
        if n < int(self.params["count"]):
            return None
        return self._fire(rec, n)


class FallbackSoloBurstDetector(_WindowedBurstDetector):
    name = "solo_burst"
    events = frozenset({"batch_dispatch"})

    def _match(self, rec) -> bool:
        return (
            rec.get("event") == "batch_dispatch"
            and rec.get("status") == "fallback_solo"
        )

    def _fire(self, rec, n):
        window = float(self.params["window_s"])
        reason = (
            f"{n} fallback_solo batch dispatches inside {window:g}s "
            "(shared dispatch path failing repeatedly)"
        )
        evidence = {
            "fallbacks": n,
            "window_s": _r(window),
            "batch_id": rec.get("batch_id"),
            "error": rec.get("error"),
        }
        return reason, evidence


class LeaseChurnDetector(_WindowedBurstDetector):
    name = "lease_churn"
    events = frozenset({"lease_expire", "chunk_reassign", "lease_split"})

    def _fire(self, rec, n):
        window = float(self.params["window_s"])
        reason = (
            f"{n} lease lifecycle events (expire/reassign/split) "
            f"inside {window:g}s — ranks dying or thrashing work"
        )
        evidence = {
            "churn": n,
            "window_s": _r(window),
            "last_event": rec.get("event"),
            "rank": rec.get("rank"),
            "range": rec.get("range"),
        }
        return reason, evidence


DETECTORS: tuple = (
    SloBreachDetector,
    LatencySpikeDetector,
    QueueSaturationDetector,
    WatchdogDetector,
    RetryExhaustionDetector,
    FallbackSoloBurstDetector,
    LeaseChurnDetector,
)

# the stable detector-name order metric pre-registration and the docs
# catalog key off (derived, never hand-maintained)
DETECTOR_NAMES: tuple = tuple(d.name for d in DETECTORS)


class DetectorSet:
    """Every detector plus the per-detector dedup fold, over one
    process's journal stream.

    Not internally locked: the journal tap calls :meth:`observe` under
    the journal's write lock (replay is single-threaded), exactly the
    :class:`~specpride_tpu.autotune.signals.SignalState` contract.

    ``observe`` returns the list of POST-DEDUP incident payloads this
    record triggered (usually empty) — each a dict ready to journal as
    an ``incident`` event modulo the host-owned fields (``mode``,
    ``bundled``, ``bundle_dir``).  Suppressed firings only bump the
    per-detector counter; the count rides the NEXT journaled incident
    as its ``suppressed`` field, so a flapping window is still fully
    accounted for in the stream."""

    def __init__(self, params: dict | None = None):
        merged = {
            k: dict(v) if isinstance(v, dict) else v
            for k, v in DEFAULT_PARAMS.items()
        }
        for key, val in (params or {}).items():
            if isinstance(val, dict) and isinstance(merged.get(key), dict):
                merged[key].update(val)
            else:
                merged[key] = val
        self.params = merged
        self.cooldown_s = float(merged["cooldown_s"])
        self.detectors = [cls(merged[cls.name]) for cls in DETECTORS]
        # detector -> trigger clock of the last JOURNALED incident
        self._last_fire: dict[str, float] = {}
        # detector -> firings swallowed since that incident
        self._suppressed: dict[str, int] = {}
        self.fired = 0
        self.suppressed = 0

    def observe(self, rec) -> list[dict]:
        """Fold one journal record; returns the incidents to journal.
        ``incident`` events themselves are ignored — the recorder's own
        output must never feed back into the detectors."""
        if not isinstance(rec, dict) or rec.get("event") == "incident":
            return []
        mono = rec.get("mono")
        if not isinstance(mono, (int, float)):
            return []
        out: list[dict] = []
        for det in self.detectors:
            try:
                got = det.observe(rec, float(mono))
            except Exception:  # noqa: BLE001 - a detector bug must not
                continue       # take the stream fold down
            if got is None:
                continue
            reason, evidence = got
            clock = _r(mono)
            last = self._last_fire.get(det.name)
            if last is not None and clock - last < self.cooldown_s:
                # dedup window: swallow, account, move on — keyed on
                # the trigger clock so replay reproduces the decision
                self._suppressed[det.name] = (
                    self._suppressed.get(det.name, 0) + 1
                )
                self.suppressed += 1
                continue
            self._last_fire[det.name] = clock
            self.fired += 1
            tid = rec.get("trace_id") or derived_trace_id(
                det.name, clock
            )
            out.append({
                "detector": det.name,
                "incident_id": incident_id(det.name, clock),
                "reason": reason,
                "clock": clock,
                "evidence": evidence,
                "trace_id": tid,
                "suppressed": self._suppressed.pop(det.name, 0),
            })
        return out

    def status(self) -> dict:
        """Live counters for ``serve status`` / the recorder."""
        return {
            "fired": self.fired,
            "suppressed": self.suppressed,
            "detectors": list(DETECTOR_NAMES),
            "cooldown_s": self.cooldown_s,
        }
