"""Metrics registry: named counters / gauges / histograms with labels.

Two export views of one registry: the Prometheus textfile format
(``--metrics-out FILE``, consumable by node_exporter's textfile collector)
and a plain JSON dict (folded into the run journal's ``run_end`` event).
Counters are cumulative over the registry's lifetime — Prometheus
semantics — so re-exporting after more work is monotone, and rewriting
the textfile is idempotent for an unchanged registry.

Thread contract: every mutator (``inc`` / ``set`` / ``observe``) and
every export view locks per metric, so a live scraper (the serving
daemon's ``/metrics`` endpoint, ``observability.exporter``) can render
the registry WHILE the dispatch lane and async-fetch threads update it
— no torn histogram states, no dict-changed-during-iteration.  The
registry-level ``_metrics`` index has its own lock.  Multi-job
processes keep ONE registry resident (Prometheus counters must be
process-monotone); per-job attribution is snapshot-and-diff
(``device_counters_snapshot`` + ``device_summary(since=...)``), never
a reset.
"""

from __future__ import annotations

import math
import os
import threading

# seconds buckets sized for dispatch/transfer latencies: sub-ms XLA calls
# up to multi-second tunneled round trips
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    def __init__(self, kind: str, name: str, help: str, label_names: tuple):
        self.kind = kind
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        # label-values tuple -> float (counter/gauge) or histogram state
        self.samples: dict[tuple, object] = {}
        # guards `samples` (and histogram state) against a concurrent
        # scrape: per metric, so the dispatch hot path never contends
        # with unrelated metrics
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.label_names)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def clear(self) -> None:
        """Drop every labeled sample (the live exporter resets ephemeral
        label sets — per-client queue depths — each scrape, so departed
        clients don't accumulate as stale series forever)."""
        with self._lock:
            self.samples.clear()


class Counter(_Metric):
    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (got {n})")
        key = self._key(labels)
        with self._lock:
            self.samples[key] = self.samples.get(key, 0.0) + n

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self.samples.get(key, 0.0))


class Gauge(_Metric):
    def set(self, v: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self.samples[key] = float(v)

    def zero_all(self) -> None:
        """Reset every existing labeled sample to 0 (keeps the series
        alive — a scraper sees in-flight drop to 0, not disappear)."""
        with self._lock:
            for key in self.samples:
                self.samples[key] = 0.0

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self.samples.get(key, 0.0))


class _HistState:
    __slots__ = ("counts", "total", "n", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.total = 0.0
        self.n = 0
        # bucket index (len(buckets) = +Inf) -> (labels, value): the
        # LAST exemplar observed into each bucket, rendered OpenMetrics-
        # style after the bucket line — a latency outlier on /metrics
        # names the trace_id that caused it
        self.exemplars: dict[int, tuple[dict, float]] = {}


class Histogram(_Metric):
    def __init__(self, kind, name, help, label_names, buckets):
        super().__init__(kind, name, help, label_names)
        self.buckets = tuple(sorted(buckets))

    def observe(
        self, v: float, exemplar: dict | None = None, **labels
    ) -> None:
        """Fold one observation in.  ``exemplar`` (e.g.
        ``{"trace_id": ...}``) attaches to the bucket the value lands
        in, last-writer-wins — the OpenMetrics affordance that links a
        histogram outlier back to its full distributed trace."""
        key = self._key(labels)
        with self._lock:
            st = self.samples.get(key)
            if st is None:
                st = self.samples[key] = _HistState(len(self.buckets))
            bucket = len(self.buckets)  # +Inf
            for i, le in enumerate(self.buckets):
                if v <= le:
                    st.counts[i] += 1
                    bucket = i
                    break
            st.total += v
            st.n += 1
            if exemplar:
                st.exemplars[bucket] = (dict(exemplar), float(v))


def _fmt_exemplar(ex: "tuple[dict, float] | None") -> str:
    """OpenMetrics exemplar suffix for a ``_bucket`` line (empty when
    the bucket has none): `` # {trace_id="..."} <value>`` — the hook
    that makes a latency outlier one ``specpride trace --trace-id``
    away from its full cross-process timeline."""
    if not ex:
        return ""
    labels, value = ex
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return f" # {{{inner}}} {_fmt(value)}"


def _sample_lines(m: _Metric, extra: tuple = ()) -> list[str]:
    """Render one metric's sample lines (no HELP/TYPE) from a consistent
    under-lock snapshot; histogram states copy so the cum-bucket math
    reads a frozen view even while observes continue.  ``extra`` is a
    tuple of ``(label, value)`` pairs prepended to every sample — the
    per-worker registry merge labels each lane's series with it."""
    with m._lock:
        samples = {
            key: (
                (tuple(st.counts), st.total, st.n, dict(st.exemplars))
                if isinstance(m, Histogram)
                else st
            )
            for key, st in m.samples.items()
        }
    if not samples:
        return []
    prefix = ",".join(
        f'{ln}="{_escape_label(lv)}"' for ln, lv in extra
    )
    lines: list[str] = []
    name = m.name
    for key in sorted(samples):
        labelstr = ",".join(
            filter(None, [prefix] + [
                f'{ln}="{_escape_label(lv)}"'
                for ln, lv in zip(m.label_names, key)
            ])
        )
        if isinstance(m, Histogram):
            counts, total, n, exemplars = samples[key]
            cum = 0
            for i, (le, c) in enumerate(zip(m.buckets, counts)):
                cum += c
                blabel = ",".join(
                    filter(None, [labelstr, f'le="{_fmt(le)}"'])
                )
                lines.append(
                    f"{name}_bucket{{{blabel}}} {cum}"
                    f"{_fmt_exemplar(exemplars.get(i))}"
                )
            blabel = ",".join(filter(None, [labelstr, 'le="+Inf"']))
            lines.append(
                f"{name}_bucket{{{blabel}}} {n}"
                f"{_fmt_exemplar(exemplars.get(len(m.buckets)))}"
            )
            base = f"{{{labelstr}}}" if labelstr else ""
            lines.append(f"{name}_sum{base} {_fmt(total)}")
            lines.append(f"{name}_count{base} {n}")
        else:
            base = f"{{{labelstr}}}" if labelstr else ""
            lines.append(f"{name}{base} {_fmt(samples[key])}")
    return lines


def render_labeled(
    registries: "dict[str, MetricsRegistry]", label: str = "worker"
) -> str:
    """One Prometheus exposition over SEVERAL registries carrying the
    SAME metric names — the serving daemon's per-worker backend
    registries.  Each metric renders ONE HELP/TYPE header (a duplicate
    TYPE per registry would fail any strict scraper) and every sample
    gains ``label="<registry key>"``, so per-lane dispatch counters,
    latency histograms and memory watermarks stay distinguishable
    without colliding.  Registries disagreeing on a metric's kind or
    label set raise — that is schema drift, not a render concern."""
    by_name: dict[str, list] = {}
    for key in sorted(registries):
        reg = registries[key]
        for m in reg._sorted_metrics():
            by_name.setdefault(m.name, []).append((key, m))
    lines: list[str] = []
    for name in sorted(by_name):
        group = by_name[name]
        kind, label_names = group[0][1].kind, group[0][1].label_names
        for _, m in group[1:]:
            if m.kind != kind or m.label_names != label_names:
                raise ValueError(
                    f"metric {name} disagrees across registries: "
                    f"{m.kind}{m.label_names} vs {kind}{label_names}"
                )
        sample_lines: list[str] = []
        for key, m in group:
            sample_lines.extend(_sample_lines(m, extra=((label, key),)))
        if not sample_lines:
            continue
        help_ = next((m.help for _, m in group if m.help), "")
        if help_:
            lines.append(f"# HELP {name} {_escape_help(help_)}")
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(sample_lines)
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._index_lock = threading.Lock()

    def _register(self, cls, kind, name, help, labels, **kw) -> _Metric:
        with self._index_lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name} re-registered as {kind}"
                        f"{tuple(labels)} (was {m.kind}{m.label_names})"
                    )
                return m
            m = self._metrics[name] = cls(
                kind, name, help, tuple(labels), **kw
            )
            return m

    def _sorted_metrics(self) -> list:
        with self._index_lock:
            return [self._metrics[name] for name in sorted(self._metrics)]

    def counter(self, name: str, help: str = "", labels=()) -> Counter:
        return self._register(Counter, "counter", name, help, labels)

    def gauge(self, name: str, help: str = "", labels=()) -> Gauge:
        return self._register(Gauge, "gauge", name, help, labels)

    def histogram(
        self, name: str, help: str = "", labels=(),
        buckets=DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(
            Histogram, "histogram", name, help, labels, buckets=buckets
        )

    # -- export views ---------------------------------------------------

    def to_prometheus_text(self) -> str:
        lines: list[str] = []
        for m in self._sorted_metrics():
            sample_lines = _sample_lines(m)
            if not sample_lines:
                # registered but never touched: a bare TYPE line with no
                # samples is legal but pure noise — skip it
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(sample_lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def write_textfile(self, path: str) -> None:
        """Atomic rewrite (tmp + rename): a scraper never reads a torn
        file, and re-export replaces — never appends to — the old view."""
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(self.to_prometheus_text())
        os.replace(tmp, path)

    def to_json(self) -> dict:
        out: dict = {}
        for m in self._sorted_metrics():
            with m._lock:
                if isinstance(m, Histogram):
                    out[m.name] = {
                        "|".join(key) or "": {"sum": st.total, "count": st.n}
                        for key, st in m.samples.items()
                    }
                else:
                    out[m.name] = {
                        "|".join(key) or "": v
                        for key, v in m.samples.items()
                    }
        return out

    def sum_counter(self, name: str) -> float:
        """Total over all label combinations (0.0 when never registered)."""
        with self._index_lock:
            m = self._metrics.get(name)
        if m is None or isinstance(m, Histogram):
            return 0.0
        with m._lock:
            return float(sum(m.samples.values()))


# -- the device schema both backends share ------------------------------

_DEVICE_KEYS = (
    "compiles", "dispatches", "bytes_h2d", "bytes_d2h",
    "pack_real_elements", "pack_padded_elements", "padding_waste_frac",
    "rows_real", "rows_padded", "bucket_occupancy_frac",
    "device_peak_bytes_in_use",
)


# the per-(kernel)-labeled counters device_summary folds; snapshot-and-
# diff these when one registry outlives a single run (the serving
# daemon's resident backend keeps ONE registry so /metrics stays
# Prometheus-monotone — run_end must still report each job's OWN traffic)
_DEVICE_COUNTERS = (
    "specpride_compiles_total",
    "specpride_dispatches_total",
    "specpride_bytes_h2d_total",
    "specpride_bytes_d2h_total",
    "specpride_pack_real_elements_total",
    "specpride_pack_padded_elements_total",
    "specpride_rows_real_total",
    "specpride_rows_padded_total",
)


def device_counters_snapshot(registry: MetricsRegistry | None) -> dict:
    """Point-in-time totals of the device counters, for
    ``device_summary(since=...)`` diffs (the same pattern as the
    compile-cache / plan-cache run_end deltas)."""
    if registry is None:
        return {}
    return {name: registry.sum_counter(name) for name in _DEVICE_COUNTERS}


def device_summary(
    registry: MetricsRegistry | None, since: dict | None = None
) -> dict:
    """Scalar device-telemetry dict with a FIXED key set, for the journal's
    ``run_end.device`` field.  A numpy-backend run (no registry, or one the
    device instrumentation never touched) reports the same keys as zeros,
    so oracle-vs-device journals diff cleanly.

    ``since`` (a ``device_counters_snapshot``): report only the traffic
    AFTER the snapshot — a long-lived multi-job process (the serving
    daemon) attributes counters to the job that caused them without
    resetting the resident registry mid-flight.  The peak-memory gauge is
    a process watermark and reports its absolute value either way."""
    out = {k: 0 for k in _DEVICE_KEYS}
    if registry is None:
        return out
    since = since or {}

    def total(name: str) -> float:
        return registry.sum_counter(name) - float(since.get(name, 0))

    out["compiles"] = int(total("specpride_compiles_total"))
    out["dispatches"] = int(total("specpride_dispatches_total"))
    out["bytes_h2d"] = int(total("specpride_bytes_h2d_total"))
    out["bytes_d2h"] = int(total("specpride_bytes_d2h_total"))
    real = total("specpride_pack_real_elements_total")
    padded = total("specpride_pack_padded_elements_total")
    out["pack_real_elements"] = int(real)
    out["pack_padded_elements"] = int(padded)
    out["padding_waste_frac"] = (
        round(1.0 - real / padded, 4) if padded > 0 else 0.0
    )
    rows_r = total("specpride_rows_real_total")
    rows_p = total("specpride_rows_padded_total")
    out["rows_real"] = int(rows_r)
    out["rows_padded"] = int(rows_p)
    out["bucket_occupancy_frac"] = (
        round(rows_r / rows_p, 4) if rows_p > 0 else 0.0
    )
    # read-only probe: must not register the gauge as a side effect
    with registry._index_lock:
        peak = registry._metrics.get("specpride_device_peak_bytes_in_use")
    if peak is not None:
        with peak._lock:
            values = list(peak.samples.values())
        out["device_peak_bytes_in_use"] = int(max(values) if values else 0)
    return out


def export_run_metrics(
    registry: MetricsRegistry, stats, device: dict
) -> None:
    """Fold one run's RunStats + device summary into ``registry`` so the
    textfile export carries the full picture.  Counters inc (cumulative
    across runs sharing a registry); phase seconds and summary fractions
    are gauges (point-in-time views of the latest run)."""
    for name, n in stats.counters.items():
        registry.counter(
            f"specpride_run_{name}_total",
            f"run counter '{name}' accumulated across runs",
        ).inc(n)
    for phase, secs in stats.phases.items():
        registry.counter(
            "specpride_phase_seconds_total",
            "per-phase wall seconds accumulated across runs",
            labels=("phase",),
        ).inc(secs, phase=phase)
    registry.gauge(
        "specpride_padding_waste_frac",
        "fraction of packed device elements that were padding (last run)",
    ).set(device["padding_waste_frac"])
    registry.gauge(
        "specpride_bucket_occupancy_frac",
        "real rows / padded rows across device dispatches (last run)",
    ).set(device["bucket_occupancy_frac"])
    registry.gauge(
        "specpride_run_elapsed_seconds", "wall time of the last run"
    ).set(stats.elapsed)
