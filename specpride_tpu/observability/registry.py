"""Metrics registry: named counters / gauges / histograms with labels.

Two export views of one registry: the Prometheus textfile format
(``--metrics-out FILE``, consumable by node_exporter's textfile collector)
and a plain JSON dict (folded into the run journal's ``run_end`` event).
Counters are cumulative over the registry's lifetime — Prometheus
semantics — so re-exporting after more work is monotone, and rewriting
the textfile is idempotent for an unchanged registry.
"""

from __future__ import annotations

import math
import os

# seconds buckets sized for dispatch/transfer latencies: sub-ms XLA calls
# up to multi-second tunneled round trips
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    def __init__(self, kind: str, name: str, help: str, label_names: tuple):
        self.kind = kind
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        # label-values tuple -> float (counter/gauge) or histogram state
        self.samples: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.label_names)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)


class Counter(_Metric):
    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (got {n})")
        key = self._key(labels)
        self.samples[key] = self.samples.get(key, 0.0) + n

    def value(self, **labels) -> float:
        return float(self.samples.get(self._key(labels), 0.0))


class Gauge(_Metric):
    def set(self, v: float, **labels) -> None:
        self.samples[self._key(labels)] = float(v)

    def value(self, **labels) -> float:
        return float(self.samples.get(self._key(labels), 0.0))


class _HistState:
    __slots__ = ("counts", "total", "n")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.total = 0.0
        self.n = 0


class Histogram(_Metric):
    def __init__(self, kind, name, help, label_names, buckets):
        super().__init__(kind, name, help, label_names)
        self.buckets = tuple(sorted(buckets))

    def observe(self, v: float, **labels) -> None:
        key = self._key(labels)
        st = self.samples.get(key)
        if st is None:
            st = self.samples[key] = _HistState(len(self.buckets))
        for i, le in enumerate(self.buckets):
            if v <= le:
                st.counts[i] += 1
                break
        st.total += v
        st.n += 1


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}

    def _register(self, cls, kind, name, help, labels, **kw) -> _Metric:
        m = self._metrics.get(name)
        if m is not None:
            if m.kind != kind or m.label_names != tuple(labels):
                raise ValueError(
                    f"metric {name} re-registered as {kind}"
                    f"{tuple(labels)} (was {m.kind}{m.label_names})"
                )
            return m
        m = self._metrics[name] = cls(kind, name, help, tuple(labels), **kw)
        return m

    def counter(self, name: str, help: str = "", labels=()) -> Counter:
        return self._register(Counter, "counter", name, help, labels)

    def gauge(self, name: str, help: str = "", labels=()) -> Gauge:
        return self._register(Gauge, "gauge", name, help, labels)

    def histogram(
        self, name: str, help: str = "", labels=(),
        buckets=DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(
            Histogram, "histogram", name, help, labels, buckets=buckets
        )

    # -- export views ---------------------------------------------------

    def to_prometheus_text(self) -> str:
        lines: list[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {name} {m.kind}")
            for key in sorted(m.samples):
                labelstr = ",".join(
                    f'{ln}="{_escape_label(lv)}"'
                    for ln, lv in zip(m.label_names, key)
                )
                if isinstance(m, Histogram):
                    st = m.samples[key]
                    cum = 0
                    for le, c in zip(m.buckets, st.counts):
                        cum += c
                        blabel = ",".join(
                            filter(None, [labelstr, f'le="{_fmt(le)}"'])
                        )
                        lines.append(f"{name}_bucket{{{blabel}}} {cum}")
                    blabel = ",".join(filter(None, [labelstr, 'le="+Inf"']))
                    lines.append(f"{name}_bucket{{{blabel}}} {st.n}")
                    base = f"{{{labelstr}}}" if labelstr else ""
                    lines.append(f"{name}_sum{base} {_fmt(st.total)}")
                    lines.append(f"{name}_count{base} {st.n}")
                else:
                    base = f"{{{labelstr}}}" if labelstr else ""
                    lines.append(f"{name}{base} {_fmt(m.samples[key])}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_textfile(self, path: str) -> None:
        """Atomic rewrite (tmp + rename): a scraper never reads a torn
        file, and re-export replaces — never appends to — the old view."""
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(self.to_prometheus_text())
        os.replace(tmp, path)

    def to_json(self) -> dict:
        out: dict = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Histogram):
                out[name] = {
                    "|".join(key) or "": {"sum": st.total, "count": st.n}
                    for key, st in m.samples.items()
                }
            else:
                out[name] = {
                    "|".join(key) or "": v for key, v in m.samples.items()
                }
        return out

    def sum_counter(self, name: str) -> float:
        """Total over all label combinations (0.0 when never registered)."""
        m = self._metrics.get(name)
        if m is None or isinstance(m, Histogram):
            return 0.0
        return float(sum(m.samples.values()))


# -- the device schema both backends share ------------------------------

_DEVICE_KEYS = (
    "compiles", "dispatches", "bytes_h2d", "bytes_d2h",
    "pack_real_elements", "pack_padded_elements", "padding_waste_frac",
    "rows_real", "rows_padded", "bucket_occupancy_frac",
    "device_peak_bytes_in_use",
)


def device_summary(registry: MetricsRegistry | None) -> dict:
    """Scalar device-telemetry dict with a FIXED key set, for the journal's
    ``run_end.device`` field.  A numpy-backend run (no registry, or one the
    device instrumentation never touched) reports the same keys as zeros,
    so oracle-vs-device journals diff cleanly."""
    out = {k: 0 for k in _DEVICE_KEYS}
    if registry is None:
        return out
    out["compiles"] = int(registry.sum_counter("specpride_compiles_total"))
    out["dispatches"] = int(
        registry.sum_counter("specpride_dispatches_total")
    )
    out["bytes_h2d"] = int(registry.sum_counter("specpride_bytes_h2d_total"))
    out["bytes_d2h"] = int(registry.sum_counter("specpride_bytes_d2h_total"))
    real = registry.sum_counter("specpride_pack_real_elements_total")
    padded = registry.sum_counter("specpride_pack_padded_elements_total")
    out["pack_real_elements"] = int(real)
    out["pack_padded_elements"] = int(padded)
    out["padding_waste_frac"] = (
        round(1.0 - real / padded, 4) if padded > 0 else 0.0
    )
    rows_r = registry.sum_counter("specpride_rows_real_total")
    rows_p = registry.sum_counter("specpride_rows_padded_total")
    out["rows_real"] = int(rows_r)
    out["rows_padded"] = int(rows_p)
    out["bucket_occupancy_frac"] = (
        round(rows_r / rows_p, 4) if rows_p > 0 else 0.0
    )
    # read-only probe: must not register the gauge as a side effect (an
    # empty metric would clutter the textfile with a sample-less TYPE line)
    peak = registry._metrics.get("specpride_device_peak_bytes_in_use")
    out["device_peak_bytes_in_use"] = int(
        max(peak.samples.values()) if peak and peak.samples else 0
    )
    return out


def export_run_metrics(
    registry: MetricsRegistry, stats, device: dict
) -> None:
    """Fold one run's RunStats + device summary into ``registry`` so the
    textfile export carries the full picture.  Counters inc (cumulative
    across runs sharing a registry); phase seconds and summary fractions
    are gauges (point-in-time views of the latest run)."""
    for name, n in stats.counters.items():
        registry.counter(
            f"specpride_run_{name}_total",
            f"run counter '{name}' accumulated across runs",
        ).inc(n)
    for phase, secs in stats.phases.items():
        registry.counter(
            "specpride_phase_seconds_total",
            "per-phase wall seconds accumulated across runs",
            labels=("phase",),
        ).inc(secs, phase=phase)
    registry.gauge(
        "specpride_padding_waste_frac",
        "fraction of packed device elements that were padding (last run)",
    ).set(device["padding_waste_frac"])
    registry.gauge(
        "specpride_bucket_occupancy_frac",
        "real rows / padded rows across device dispatches (last run)",
    ).set(device["bucket_occupancy_frac"])
    registry.gauge(
        "specpride_run_elapsed_seconds", "wall time of the last run"
    ).set(stats.elapsed)
