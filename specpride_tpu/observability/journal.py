"""Run journal: append-only JSONL event stream for live tailing and
post-mortems.

Every event is one JSON object per line with four envelope fields —
``v`` (schema version, currently 4), ``ts`` (unix seconds), ``mono``
(``time.perf_counter()`` seconds: monotonic, so interval reconstruction
— span timelines, event spacing — is immune to wall-clock jumps; only
comparable within one process run, anchored to the wall clock by the
``clock_anchor`` events), ``event`` (type name) — plus the per-type
payload listed in ``EVENT_FIELDS``.  v4 adds the **trace-context
envelope**: a journal bound to a trace (``bind_trace``) stamps
``trace_id`` (32-hex) on every event it emits, the serving daemon's
per-job events carry it explicitly (``TRACE_EVENT_FIELDS``), and
``span`` events gain ``span_id``/``parent_span_id`` so one causal tree
spans processes.  v5 adds the **autotune** decision event (the
closed-loop controller's evidence trail) and requires the elastic
heartbeat to mirror its EWMA chunk wall (``chunk_s``) — both additive;
v6 adds the **incident** event (the flight recorder's detector-firing
record, carrying the incident id + evidence payload that
``specpride incident-replay`` re-derives from the stream alone).
v1–v5 journals (no ``mono`` / no trace fields / no autotune / no
incidents) still read and validate.  An operator can ``tail -f`` a live run's journal
(every line is flushed as it is written) or feed one or more
finished/dead journals to ``specpride stats`` for an aggregate
post-mortem.

Multi-host runs write one journal per rank (``<journal>.part<id>``, the
same naming as output shards); ``expand_parts`` resolves a base path to
its rank-ordered part list the way ``merge-parts`` does for outputs.
Long-lived daemons rotate their live journal at a size bound
(``--journal-rotate-mb``) into numbered segments (``<journal>.1``,
``.2``, ...; the un-suffixed path is always the live tail);
``expand_parts`` resolves those too, oldest first, so ``stats``/
``trace`` read across segment boundaries transparently.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import time

SCHEMA_VERSION = 7

# versions read_events accepts: v2 added the monotonic `mono` envelope
# field and the `span` event; v4 added the trace-context envelope
# (trace_id / span ids) and the `clock_anchor` event; v5 added the
# `autotune` decision event and the heartbeat `chunk_s` mirror; v6
# added the `incident` event (the flight recorder's detector-firing
# record); v7 added the `result_cache` event (the content-addressed
# consensus result cache's per-run accounting).  v3 is reserved — the
# live-telemetry-plane revision was docs-only, with no envelope change,
# and the journal version skips it to keep the wire and docs version
# numbers aligned; a v3 journal reads exactly like v2.
ACCEPTED_VERSIONS = frozenset({1, 2, 3, 4, 5, 6, SCHEMA_VERSION})

# event type -> required payload fields (the envelope v/ts/mono/event is
# implied; extra fields are allowed — the schema is additive within a
# version)
EVENT_FIELDS: dict[str, frozenset] = {
    "run_start": frozenset({"command", "method", "backend", "n_clusters"}),
    "chunk_start": frozenset({"chunk_index", "n_clusters"}),
    "chunk_done": frozenset(
        {"chunk_index", "n_clusters", "n_representatives", "elapsed_s",
         "clusters_per_sec"}
    ),
    "compile": frozenset({"kernel", "shape_key"}),
    "dispatch": frozenset({"kernel", "rows", "padded_rows"}),
    "checkpoint_write": frozenset({"n_done", "output_bytes"}),
    "resume": frozenset({"n_done"}),
    "qc_failure": frozenset({"cluster_ids"}),
    "skipped_clusters": frozenset({"cluster_ids"}),
    # device-availability routing: a backend substituted an equivalent
    # execution path for the requested layout (e.g. gap-average on a
    # CPU-only host) — emitted once per backend per decision
    "routing": frozenset({"method", "path", "reason"}),
    # reduced-precision packed paths (--precision): emitted once per
    # backend per method with the channel encodings a run actually
    # shipped (the pack-time probes decide per workload), and once per
    # run by the CLI's QC-cosine tolerance gate with its verdict
    "precision": frozenset({"method", "precision"}),
    # robustness layer (specpride_tpu.robustness): an injected fault
    # fired at a named site; each must pair with a later recovery event
    # (retry / degrade / resume_repair / quarantine / skipped_clusters)
    "fault": frozenset({"site", "kind", "visit"}),
    # a transient failure was retried with backoff at a wrapper site
    "retry": frozenset({"site", "attempt", "backoff_s"}),
    # graceful degradation: a chunk was split after device OOM, or
    # rerouted to the numpy backend after repeated device failure
    "degrade": frozenset({"action", "reason"}),
    # resume found the output/manifest damaged and repaired (truncated a
    # torn tail) or restarted (hash mismatch, unreadable manifest)
    "resume_repair": frozenset({"action", "reason"}),
    # a malformed MGF block was diverted to <output>.quarantine.mgf
    "quarantine": frozenset({"path", "reason"}),
    # a lane section exceeded --watchdog-timeout
    "watchdog_stall": frozenset({"lane", "elapsed_s"}),
    # elastic multi-host (specpride_tpu.parallel.coordinator): one rank
    # liveness beat — renews held leases and rewrites the rank's
    # heartbeat file; `holding` lists the range ids leased right now
    "heartbeat": frozenset({"rank"}),
    # a rank claimed a chunk range under a lease (takeover=True when the
    # range carries a dead rank's partial state to resume)
    "lease_claim": frozenset({"rank", "range"}),
    # an observer found a lease expired past its TTL + grace: `rank` is
    # the DEAD holder, `observed_by` the survivor about to reassign —
    # every lease_expire must pair with a chunk_reassign (audited by
    # `parallel.elastic.audit_elastic` and the chaos CI pass)
    "lease_expire": frozenset({"rank", "range"}),
    # the surviving rank reclaimed the dead rank's uncommitted chunks
    # (or, with `via="lease_split"`, claimed a split-off live tail)
    "chunk_reassign": frozenset({"range", "from_rank", "to_rank"}),
    # live work-stealing (tier 2): the DONOR ratified a split of its own
    # range at a chunk boundary — the suffix [split_at, stop) is now
    # overlay range `new_range`, and every lease_split must pair with a
    # chunk_reassign for that new range (audited like lease_expire)
    "lease_split": frozenset({"range", "new_range", "rank", "split_at"}),
    # fleet supervisor (`specpride fleet`): a rank process was spawned
    # (boot, replacement for a dead rank, or a warm spare scaled up) or
    # retired (excess capacity scaled down)
    "rank_spawn": frozenset({"pid"}),
    "rank_retire": frozenset({"pid", "reason"}),
    # cross-process clock anchoring (v4): one high-precision wall<->mono
    # pair — `wall` captured between two perf_counter reads, the
    # envelope `mono` overridden to their midpoint, `uncertainty_s`
    # half their distance — emitted at journal open and re-emitted on
    # heartbeat cadence, so the trace merger can align per-process
    # monotonic timelines onto ONE wall axis with a bounded skew
    # (observability.traceplane.clock_anchor_fit)
    "clock_anchor": frozenset({"wall", "uncertainty_s"}),
    # warm-start subsystem (specpride_tpu.warmstart): how the persistent
    # compilation cache resolved for this run (dir, or the reason it
    # stayed off) — post-mortems must be able to tell cached from cold
    "compile_cache": frozenset({"enabled"}),
    # one AOT bucket-shape warmup compile: persistent-cache hit vs a
    # fresh XLA compile, and how long it took
    "warmup": frozenset({"kernel", "cache_hit", "seconds"}),
    # serving daemon (specpride_tpu.serve): lifecycle + per-job
    # telemetry.  The daemon's own journal is one run (run_start
    # command="serve" ... run_end at drain) with these in between;
    # each JOB additionally writes its own --journal like any CLI run.
    # Worker-pool daemons add a `worker` lane id to job_start/job_done
    # (and to each job's own run_end) — additive fields, so single-lane
    # and pre-pool journals keep validating; `specpride stats` groups
    # the serving view by worker when the field is present.
    "serve_start": frozenset({"socket", "max_queue"}),
    "job_queued": frozenset({"job_id", "client"}),
    "job_start": frozenset({"job_id"}),
    # job_done optionally carries the SLO evaluation (`slo_objective_s`,
    # `slo_latency_s`, `slo_ok`) when the daemon booted with --slo —
    # additive fields, so pre-SLO consumers keep validating
    "job_done": frozenset({"job_id", "status", "wall_s"}),
    "job_rejected": frozenset({"reason"}),
    # cross-job micro-batching (serve.batcher): one SHARED packed-bucket
    # dispatch coalescing several jobs' cluster work.  `jobs` lists the
    # member job ids; `n_clusters` is the merged size; `window_wait_s`
    # the collection wait; occupancy/fresh-compile/plan-cache deltas
    # attribute the one dispatch's device work that no single job's
    # run_end can claim.  status="shared" ran the coalesced dispatch;
    # "fallback_solo" means the shared pass failed and every member ran
    # solo (additive fields — pre-batching consumers keep validating).
    "batch_dispatch": frozenset(
        {"batch_id", "jobs", "n_jobs", "n_clusters", "window_wait_s",
         "status"}
    ),
    "serve_drain": frozenset({"n_rejected"}),
    # closed-loop autotune controller (specpride_tpu.autotune, v5): one
    # policy decision over one knob.  `mode` is the kill-switch position
    # (observe|on); `acted` False means the decision was journaled
    # without actuating (observe mode, or a no-change tick worth
    # recording); `old`/`new` are the knob values; `reason` the policy's
    # one-line justification; `signal` the windowed signal snapshot that
    # triggered it — together the full evidence payload autotune-replay
    # refolds to reproduce the decision
    "autotune": frozenset(
        {"knob", "mode", "old", "new", "reason", "signal", "acted"}
    ),
    # flight recorder (specpride_tpu.observability.flightrec, v6): one
    # health-detector firing.  `detector` names the pure fold that
    # fired; `reason` is its one-line justification; `clock` the
    # triggering record's mono (the replay key — the event's own
    # envelope mono is when the recorder thread got to writing it);
    # `mode` the kill-switch position (observe|on); `bundled` whether
    # an on-disk incident bundle was written (mode on only — observe
    # journals the firing without dumping).  v6 gates the id/evidence
    # payload (V6_EVENT_FIELDS below); optional fields: `bundle_dir`
    # (the atomic bundle's final path), `suppressed` (firings the
    # dedup window swallowed since the last journaled incident).
    # `specpride incident-replay` re-derives every firing and its
    # dedup decision bit-exact from the preceding stream alone.
    "incident": frozenset({"detector", "reason", "clock", "mode",
                           "bundled"}),
    # content-addressed result cache (specpride_tpu.cache, v7): one
    # per-run accounting record emitted just before run_end when a run
    # consulted the cache.  `hits`/`misses` partition the consulted
    # clusters; `populated` counts entries written post-QC;
    # `evictions` the local-tier LRU evictions this run forced;
    # `bytes_saved` the peak bytes the hits did not recompute.
    # Optional fields: `shared_hits` (hits served by the shared Store
    # tier), `corrupt` (entries quarantined on digest mismatch —
    # served as misses, never as results), `entries`/`bytes` (local
    # tier occupancy after the run).
    "result_cache": frozenset(
        {"hits", "misses", "populated", "evictions", "bytes_saved"}
    ),
    # on-demand device profiling (`specpride profile` against a live
    # daemon): one bounded jax.profiler capture window
    "profile_start": frozenset({"seconds"}),
    "profile_done": frozenset({"seconds", "trace_dir"}),
    "bench_run": frozenset({"method", "phases_s"}),
    "run_end": frozenset({"counters", "phases_s", "elapsed_s", "device"}),
    # v2: one finished tracing span (observability.tracing).  The span's
    # end time is the envelope `mono`; start = mono - dur_s.  Optional
    # `labels` carries the per-span annotations (kernel, rows, ...);
    # v4 adds `span_id`/`parent_span_id` when a trace context is
    # installed, so the causal tree survives process boundaries.
    "span": frozenset({"name", "dur_s", "depth"}),
}

# v4 trace-context envelope: events that MUST carry their causal trace
# fields from schema v4 on (older journals validate without them — the
# requirement is version-gated in validate_event).  The serving
# daemon's journal holds many concurrent traces, so its per-job events
# name theirs explicitly; per-run journals stamp every event via
# `Journal.bind_trace` instead.  `batch_dispatch` carries `trace_ids`
# (plural): one shared dispatch serves members of SEVERAL traces.
# `specpride lint` (journal-schema) enforces these at every emit site.
TRACE_EVENT_FIELDS: dict[str, frozenset] = {
    "job_queued": frozenset({"trace_id"}),
    "job_start": frozenset({"trace_id"}),
    "job_done": frozenset({"trace_id"}),
    "batch_dispatch": frozenset({"trace_ids"}),
    # an autotune decision cites the traces active in its signal window
    # as evidence (possibly empty — e.g. a fleet-spares decision between
    # jobs); the field itself is mandatory from v5 on
    "autotune": frozenset({"trace_ids"}),
    # an incident joins the causal timeline through the newest evidence
    # record that carried a trace id (or a content-derived id when none
    # did — deterministic either way, so replay reproduces it)
    "incident": frozenset({"trace_id"}),
}

# v5 additive requirements on PRE-EXISTING events: fields that became
# mandatory at schema v5 but must not invalidate committed v4 journals
# (the requirement is version-gated in validate_event, exactly like the
# v4 trace envelope above).  heartbeat `chunk_s` is the per-rank EWMA
# chunk wall steal targeting already consumes from the heartbeat STORE
# record — mirrored into the journal event so post-mortems and the
# elastic-range autotune policy read the same signal.  `specpride lint`
# (journal-schema) enforces these at every emit site too.
V5_EVENT_FIELDS: dict[str, frozenset] = {
    "heartbeat": frozenset({"chunk_s"}),
}

# v6 additive requirements: the `incident` event's identity + evidence
# payload — gated exactly like the v5 fields above so the validator
# (and `specpride lint`) treat every additive revision uniformly.
# `incident_id` is content-derived (detector + trigger clock), so two
# processes replaying the same stream mint the same id; `evidence` is
# the detector's recorded state excerpt that incident-replay refolds.
V6_EVENT_FIELDS: dict[str, frozenset] = {
    "incident": frozenset({"incident_id", "evidence"}),
}

_TRACE_ID_RE = re.compile(r"[0-9a-f]{32}")
_SPAN_ID_RE = re.compile(r"[0-9a-f]{16}")


def _json_default(obj):
    """Journals must never crash a run over a numpy scalar in a payload."""
    for cast in (int, float):
        try:
            return cast(obj)
        except (TypeError, ValueError):
            continue
    return str(obj)


class Journal:
    """Append-only JSONL event writer.  Line-buffered so each event hits
    the filesystem as one complete line — tailable mid-run, and a crash
    loses at most the event being written.

    ``rotate_mb`` > 0 bounds the live file: once an emit pushes it past
    the bound, the file is renamed to the next numbered segment
    (``<path>.1``, ``.2``, ...) and a fresh live file opens — a
    days-long daemon journal stays bounded, and readers
    (``expand_parts`` / ``stats --follow``) walk the segments in order.

    ``bind_trace(trace_id)`` stamps the v4 causal envelope: every
    subsequent event carries ``trace_id`` unless the emit names its own
    (one run journal = one trace; the multi-trace serving daemon leaves
    its journal unbound and stamps per-job events explicitly).

    ``set_tap(fn)`` installs an in-process observer called with every
    record WHILE THE WRITE LOCK IS HELD — so the observer's fold order
    is exactly the file's line order.  The autotune signal layer taps
    its own journal this way, and pairs it with :meth:`emit_atomic`:
    the controller snapshots its tapped state, decides, and writes the
    decision in ONE critical section, so no concurrent worker event can
    land between the evidence snapshot and the decision line — which is
    what makes ``specpride autotune-replay`` deterministic."""

    enabled = True

    def __init__(self, path: str | os.PathLike, rotate_mb: float = 0.0):
        self.path = str(path)
        self.trace_id: str | None = None
        # in-process observers of every emitted record (called under
        # the write lock; must be fast and must never raise into the
        # emit).  A tuple, not a list: emits iterate it lock-free with
        # respect to attach/detach, which swap the whole tuple under
        # the lock — an observer set mutation never tears an emit's
        # iteration.  Fire order is attach order (the autotune signal
        # fold and the flight recorder may both tap one journal).
        self._taps: tuple = ()
        self.rotate_bytes = int(max(float(rotate_mb), 0.0) * 1024 * 1024)
        # one journal is shared by the CLI thread, the pipelined executor's
        # packer thread, and the fetch pool; a lock keeps each event line
        # whole (TextIOWrapper gives no cross-thread write atomicity)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", buffering=1, encoding="utf-8")
        self._bytes = 0
        # a kill mid-write leaves a torn final line with no newline; a
        # resumed run appending straight onto it would corrupt BOTH its
        # own run_start and the torn event — heal the seam first
        try:
            with open(self.path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                self._bytes = fh.tell()
                if fh.tell() > 0:
                    fh.seek(-1, os.SEEK_END)
                    if fh.read(1) != b"\n":
                        self._fh.write("\n")
                        self._bytes += 1
        except OSError:
            pass

    def bind_trace(self, trace_id: str | None) -> None:
        """Stamp ``trace_id`` on every event emitted from now on (the
        per-run causal envelope; None unbinds)."""
        self.trace_id = trace_id

    def set_tap(self, tap) -> None:
        """Install (or clear, with None) the per-record observer set.
        The legacy single-observer seam: REPLACES every installed tap —
        hosts with more than one observer detach their own via
        :meth:`detach_tap` instead.  Tap exceptions are swallowed: a
        broken observer must never take the journal — and the run —
        down with it."""
        with self._lock:
            self._taps = () if tap is None else (tap,)

    def detach_tap(self, tap) -> None:
        """Remove ONE observer, leaving the others installed — the
        multi-tap counterpart of ``set_tap(None)`` (the autotune
        controller and the flight recorder detach independently at
        drain, in either order).  Unknown taps are ignored.  Matched by
        equality, not identity: ``obj.method`` mints a fresh bound-
        method object on every attribute access, so the identity of the
        attach-time reference is unrecoverable at detach time — bound-
        method ``==`` compares the underlying (object, function) pair
        instead."""
        with self._lock:
            self._taps = tuple(t for t in self._taps if t != tap)

    def attach_tap(self, tap) -> None:
        """Install the per-record observer WITH CATCH-UP: every record
        already in the journal (rotated segments first, then the live
        file) is folded through ``tap`` before it goes live, all under
        the write lock so no emit can interleave.  From the observer's
        point of view its state is exactly ``fold(file so far)`` at
        every instant — the invariant the offline refold audit
        (``specpride autotune-replay``) holds live decisions to, which
        a bare :meth:`set_tap` mid-run would silently break (events
        from before the attach would be in the file but not the
        fold)."""
        with self._lock:
            self._fh.flush()
            segments = sorted(_numbered_segments(self.path))
            for path in [seg for _n, seg in segments] + [self.path]:
                try:
                    fh = open(path, encoding="utf-8")
                except OSError:
                    continue
                with fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except (ValueError, json.JSONDecodeError):
                            continue  # torn tail line mid-write
                        try:
                            tap(rec)
                        except Exception:
                            pass  # same contract as the live tap
            self._taps = self._taps + (tap,)

    def _build_rec(self, event: str, fields: dict) -> dict:
        rec = {
            "v": SCHEMA_VERSION,
            "ts": time.time(),
            "mono": time.perf_counter(),
            "event": event,
        }
        if self.trace_id is not None and "trace_id" not in fields:
            rec["trace_id"] = self.trace_id
        rec.update(fields)
        return rec

    def _write_locked(self, rec: dict) -> None:
        """Serialize + append one record; caller holds the lock.  The
        tap fires here — under the lock — so observer fold order is
        exactly file line order."""
        line = json.dumps(rec, default=_json_default) + "\n"
        # a multi-thread producer (the serving daemon's reader
        # threads) may race close(); dropping a late event beats
        # crashing the thread on a closed file
        if not self._fh.closed:
            self._fh.write(line)
            # json.dumps default ensure_ascii output is pure ASCII,
            # so the character count IS the byte count — no second
            # encode on the hot path
            self._bytes += len(line)
            if self.rotate_bytes and self._bytes >= self.rotate_bytes:
                self._rotate_locked()
        for tap in self._taps:
            try:
                tap(rec)
            except Exception:
                pass

    def emit(self, event: str, **fields) -> dict:
        rec = self._build_rec(event, fields)
        with self._lock:
            self._write_locked(rec)
        return rec

    def emit_atomic(self, build) -> dict | None:
        """Emit one event whose payload is COMPUTED under the write
        lock: ``build()`` returns ``(event, fields)`` — or None to emit
        nothing — and runs with no concurrent emit in flight, so state
        it snapshots (e.g. the tapped signal fold) cannot drift between
        snapshot and write.  This is the autotune controller's decision
        primitive: evidence snapshot + policy + journal line are one
        atomic step with respect to file order."""
        with self._lock:
            built = build()
            if built is None:
                return None
            event, fields = built
            rec = self._build_rec(event, fields)
            self._write_locked(rec)
        return rec

    def _rotate_locked(self) -> None:
        """Roll the live file over to the next numbered segment (caller
        holds the lock).  Rename-then-reopen: an event line is never
        split across segments, and a reader mid-tail finds the renamed
        segment by number (`stats --follow` handles the swap)."""
        self._fh.close()
        n = 1
        for num, _seg in _numbered_segments(self.path):
            n = max(n, num + 1)
        try:
            os.replace(self.path, f"{self.path}.{n}")
        except OSError:
            # the rename failing (exotic filesystems) must not kill the
            # run: keep appending to the oversized live file instead
            pass
        self._fh = open(self.path, "a", buffering=1, encoding="utf-8")
        self._bytes = 0
        # every segment is self-anchored: the merger fits clocks per
        # file, so a fresh segment must not degrade to the coarse
        # envelope fallback until the next cadence anchor arrives
        # (written inline — emit() would re-enter the lock)
        rec = {
            "v": SCHEMA_VERSION, "event": "clock_anchor",
            **_anchor_fields(),
        }
        rec["ts"] = rec["wall"]
        if self.trace_id is not None:
            rec["trace_id"] = self.trace_id
        line = json.dumps(rec, default=_json_default) + "\n"
        self._fh.write(line)
        self._bytes += len(line)

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NullJournal:
    """No-op stand-in so call sites never branch on '--journal given?'."""

    enabled = False
    path = None
    trace_id = None

    def bind_trace(self, trace_id: str | None) -> None:
        pass

    def set_tap(self, tap) -> None:
        pass

    def attach_tap(self, tap) -> None:
        pass

    def detach_tap(self, tap) -> None:
        pass

    def emit(self, event: str, **fields) -> dict:
        return {}

    def emit_atomic(self, build) -> dict | None:
        return None

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullJournal":
        return self

    def __exit__(self, *exc) -> None:
        pass


def open_journal(
    path: str | None, rotate_mb: float = 0.0
) -> Journal | NullJournal:
    return Journal(path, rotate_mb=rotate_mb) if path else NullJournal()


def _anchor_fields() -> dict:
    """One high-precision wall<->mono capture: ``wall`` read between two
    ``perf_counter`` reads, ``mono`` their midpoint, ``uncertainty_s``
    half the window — the ONE construction both ``emit_clock_anchor``
    and the post-rotation inline write share."""
    t0 = time.perf_counter()
    wall = time.time()
    t1 = time.perf_counter()
    return {
        "mono": (t0 + t1) / 2.0,
        "wall": wall,
        "uncertainty_s": round((t1 - t0) / 2.0, 9),
    }


def emit_clock_anchor(journal) -> dict:
    """Journal one high-precision wall<->mono pair: ``wall`` is captured
    between two ``perf_counter`` reads and the envelope ``mono``
    overridden to their midpoint, so the pair's skew is bounded by
    ``uncertainty_s`` (half the capture window) — the unit the trace
    merger's clock fit sums into its alignment bound."""
    return journal.emit("clock_anchor", **_anchor_fields())


def validate_event(rec: object) -> list[str]:
    """Schema-violation messages for one decoded journal line (empty list
    when valid)."""
    problems: list[str] = []
    if not isinstance(rec, dict):
        return [f"event is not an object: {rec!r}"]
    if rec.get("v") not in ACCEPTED_VERSIONS:
        problems.append(f"unsupported schema version {rec.get('v')!r}")
    if not isinstance(rec.get("ts"), (int, float)):
        problems.append("missing/non-numeric 'ts'")
    if rec.get("v") == 2 and not isinstance(rec.get("mono"), (int, float)):
        problems.append("missing/non-numeric 'mono' (required in v2)")
    event = rec.get("event")
    required = EVENT_FIELDS.get(event)
    if required is None:
        problems.append(f"unknown event type {event!r}")
    else:
        missing = sorted(required - rec.keys())
        if missing:
            problems.append(f"{event}: missing fields {missing}")
    # v4 trace-context envelope: the causal fields are REQUIRED on the
    # serving events from v4 on (older journals validate without them),
    # and syntactically checked wherever they appear — a malformed id
    # would silently break every cross-process join downstream
    if rec.get("v", 0) >= 4 and required is not None:
        missing = sorted(
            TRACE_EVENT_FIELDS.get(event, frozenset()) - rec.keys()
        )
        if missing:
            problems.append(
                f"{event}: missing v4 trace fields {missing}"
            )
    # v5 additive requirements on pre-existing events (heartbeat
    # chunk_s): gated exactly like the trace envelope, so committed
    # v4 journals keep validating
    if rec.get("v", 0) >= 5 and required is not None:
        missing = sorted(
            V5_EVENT_FIELDS.get(event, frozenset()) - rec.keys()
        )
        if missing:
            problems.append(f"{event}: missing v5 fields {missing}")
    # v6 additive requirements (incident identity + evidence): same
    # version gate discipline as v5
    if rec.get("v", 0) >= 6 and required is not None:
        missing = sorted(
            V6_EVENT_FIELDS.get(event, frozenset()) - rec.keys()
        )
        if missing:
            problems.append(f"{event}: missing v6 fields {missing}")
    tid = rec.get("trace_id")
    if tid is not None and not (
        isinstance(tid, str) and _TRACE_ID_RE.fullmatch(tid)
    ):
        problems.append(f"malformed trace_id {tid!r} (need 32 hex chars)")
    for key in ("span_id", "parent_span_id"):
        sid = rec.get(key)
        if sid is not None and not (
            isinstance(sid, str) and _SPAN_ID_RE.fullmatch(sid)
        ):
            problems.append(f"malformed {key} {sid!r} (need 16 hex chars)")
    return problems


def read_events(path: str) -> tuple[list[dict], list[str]]:
    """Decode one journal file.  Returns ``(events, violations)``;
    violations carry ``path:line:`` prefixes so a multi-journal report
    stays attributable."""
    events: list[dict] = []
    violations: list[str] = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                violations.append(f"{path}:{lineno}: invalid JSON ({e.msg})")
                continue
            problems = validate_event(rec)
            for p in problems:
                violations.append(f"{path}:{lineno}: {p}")
            # only schema-valid events reach the summary: consumers may then
            # index required fields without re-checking (an invalid line is
            # still reported above and fails `specpride stats`)
            if not problems:
                events.append(rec)
    return events, violations


def _numbered_segments(path: str) -> list[tuple[int, str]]:
    """``(number, file)`` for every rotated segment of EXACTLY this
    journal: the whole remainder past ``<path>.`` must be digits, so a
    rank shard's rotated segment (``x.jsonl.part00000.1``) can never be
    misread as a segment of the base ``x.jsonl``."""
    out = []
    prefix_len = len(path) + 1
    for seg in glob.glob(glob.escape(path) + ".*"):
        suffix = seg[prefix_len:]
        if suffix.isdigit():
            out.append((int(suffix), seg))
    out.sort()
    return out


def expand_segments(path: str) -> list[str]:
    """One journal's rotated segments plus the live file, oldest first:
    ``<path>.1``, ``<path>.2``, ..., ``<path>`` — the read order that
    reconstructs the stream a ``--journal-rotate-mb`` daemon rotated.
    Paths that do not exist are simply absent (a never-rotated journal
    returns just itself)."""
    out = [p for _, p in _numbered_segments(path)]
    if os.path.exists(path):
        out.append(path)
    return out


def expand_parts(path: str) -> tuple[list[str], list[str]]:
    """Resolve a journal path to its file list, rank-aware like
    ``merge-parts``: the path itself (preceded by any rotated
    ``<path>.<n>`` segments, oldest first) if it exists, else its
    ``<path>.part<id>`` shards ordered by parsed rank (NOT lexically),
    each with ITS segments.  Returns ``(paths, warnings)``; a gap in
    the rank sequence is a warning, not an error — a post-mortem of a
    dead run must still read the ranks that DID write."""
    if os.path.exists(path):
        return expand_segments(path), []
    parts = glob.glob(glob.escape(path) + ".part*")
    if not parts:
        return [], [f"no journal at {path} and no {path}.part* shards"]
    ranked, warnings = [], []
    for p in parts:
        suffix = p.rsplit(".part", 1)[1]
        if suffix.isdigit():
            ranked.append((int(suffix), p))
        elif re.fullmatch(r"\d+\.\d+", suffix):
            pass  # a part's rotated segment: expand_segments finds it
        else:
            warnings.append(f"unrecognized part name {p}")
    ranked.sort()
    ranks = [r for r, _ in ranked]
    missing = sorted(set(range(max(ranks) + 1)) - set(ranks)) if ranks else []
    if missing:
        warnings.append(
            f"{path}: rank gap — have {ranks}, missing {missing} "
            "(a rank died before writing its journal?)"
        )
    out: list[str] = []
    for _, p in ranked:
        out.extend(expand_segments(p))
    return out, warnings
