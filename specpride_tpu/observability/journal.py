"""Run journal: append-only JSONL event stream for live tailing and
post-mortems.

Every event is one JSON object per line with four envelope fields —
``v`` (schema version, currently 2), ``ts`` (unix seconds), ``mono``
(``time.perf_counter()`` seconds: monotonic, so interval reconstruction
— span timelines, event spacing — is immune to wall-clock jumps; only
comparable within one process run, anchored to ``ts`` at ``run_start``),
``event`` (type name) — plus the per-type payload listed in
``EVENT_FIELDS``.  v1 journals (no ``mono``) still read and validate.
An operator can ``tail -f`` a live run's journal (every line is flushed
as it is written) or feed one or more finished/dead journals to
``specpride stats`` for an aggregate post-mortem.

Multi-host runs write one journal per rank (``<journal>.part<id>``, the
same naming as output shards); ``expand_parts`` resolves a base path to
its rank-ordered part list the way ``merge-parts`` does for outputs.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time

SCHEMA_VERSION = 2

# versions read_events accepts: v2 added the monotonic `mono` envelope
# field and the `span` event; v1 events remain valid (additive change)
ACCEPTED_VERSIONS = frozenset({1, SCHEMA_VERSION})

# event type -> required payload fields (the envelope v/ts/mono/event is
# implied; extra fields are allowed — the schema is additive within a
# version)
EVENT_FIELDS: dict[str, frozenset] = {
    "run_start": frozenset({"command", "method", "backend", "n_clusters"}),
    "chunk_start": frozenset({"chunk_index", "n_clusters"}),
    "chunk_done": frozenset(
        {"chunk_index", "n_clusters", "n_representatives", "elapsed_s",
         "clusters_per_sec"}
    ),
    "compile": frozenset({"kernel", "shape_key"}),
    "dispatch": frozenset({"kernel", "rows", "padded_rows"}),
    "checkpoint_write": frozenset({"n_done", "output_bytes"}),
    "resume": frozenset({"n_done"}),
    "qc_failure": frozenset({"cluster_ids"}),
    "skipped_clusters": frozenset({"cluster_ids"}),
    # device-availability routing: a backend substituted an equivalent
    # execution path for the requested layout (e.g. gap-average on a
    # CPU-only host) — emitted once per backend per decision
    "routing": frozenset({"method", "path", "reason"}),
    # reduced-precision packed paths (--precision): emitted once per
    # backend per method with the channel encodings a run actually
    # shipped (the pack-time probes decide per workload), and once per
    # run by the CLI's QC-cosine tolerance gate with its verdict
    "precision": frozenset({"method", "precision"}),
    # robustness layer (specpride_tpu.robustness): an injected fault
    # fired at a named site; each must pair with a later recovery event
    # (retry / degrade / resume_repair / quarantine / skipped_clusters)
    "fault": frozenset({"site", "kind", "visit"}),
    # a transient failure was retried with backoff at a wrapper site
    "retry": frozenset({"site", "attempt", "backoff_s"}),
    # graceful degradation: a chunk was split after device OOM, or
    # rerouted to the numpy backend after repeated device failure
    "degrade": frozenset({"action", "reason"}),
    # resume found the output/manifest damaged and repaired (truncated a
    # torn tail) or restarted (hash mismatch, unreadable manifest)
    "resume_repair": frozenset({"action", "reason"}),
    # a malformed MGF block was diverted to <output>.quarantine.mgf
    "quarantine": frozenset({"path", "reason"}),
    # a lane section exceeded --watchdog-timeout
    "watchdog_stall": frozenset({"lane", "elapsed_s"}),
    # elastic multi-host (specpride_tpu.parallel.coordinator): one rank
    # liveness beat — renews held leases and rewrites the rank's
    # heartbeat file; `holding` lists the range ids leased right now
    "heartbeat": frozenset({"rank"}),
    # a rank claimed a chunk range under a lease (takeover=True when the
    # range carries a dead rank's partial state to resume)
    "lease_claim": frozenset({"rank", "range"}),
    # an observer found a lease expired past its TTL + grace: `rank` is
    # the DEAD holder, `observed_by` the survivor about to reassign —
    # every lease_expire must pair with a chunk_reassign (audited by
    # `parallel.elastic.audit_elastic` and the chaos CI pass)
    "lease_expire": frozenset({"rank", "range"}),
    # the surviving rank reclaimed the dead rank's uncommitted chunks
    # (or, with `via="lease_split"`, claimed a split-off live tail)
    "chunk_reassign": frozenset({"range", "from_rank", "to_rank"}),
    # live work-stealing (tier 2): the DONOR ratified a split of its own
    # range at a chunk boundary — the suffix [split_at, stop) is now
    # overlay range `new_range`, and every lease_split must pair with a
    # chunk_reassign for that new range (audited like lease_expire)
    "lease_split": frozenset({"range", "new_range", "rank", "split_at"}),
    # fleet supervisor (`specpride fleet`): a rank process was spawned
    # (boot, replacement for a dead rank, or a warm spare scaled up) or
    # retired (excess capacity scaled down)
    "rank_spawn": frozenset({"pid"}),
    "rank_retire": frozenset({"pid", "reason"}),
    # warm-start subsystem (specpride_tpu.warmstart): how the persistent
    # compilation cache resolved for this run (dir, or the reason it
    # stayed off) — post-mortems must be able to tell cached from cold
    "compile_cache": frozenset({"enabled"}),
    # one AOT bucket-shape warmup compile: persistent-cache hit vs a
    # fresh XLA compile, and how long it took
    "warmup": frozenset({"kernel", "cache_hit", "seconds"}),
    # serving daemon (specpride_tpu.serve): lifecycle + per-job
    # telemetry.  The daemon's own journal is one run (run_start
    # command="serve" ... run_end at drain) with these in between;
    # each JOB additionally writes its own --journal like any CLI run.
    # Worker-pool daemons add a `worker` lane id to job_start/job_done
    # (and to each job's own run_end) — additive fields, so single-lane
    # and pre-pool journals keep validating; `specpride stats` groups
    # the serving view by worker when the field is present.
    "serve_start": frozenset({"socket", "max_queue"}),
    "job_queued": frozenset({"job_id", "client"}),
    "job_start": frozenset({"job_id"}),
    # job_done optionally carries the SLO evaluation (`slo_objective_s`,
    # `slo_latency_s`, `slo_ok`) when the daemon booted with --slo —
    # additive fields, so pre-SLO consumers keep validating
    "job_done": frozenset({"job_id", "status", "wall_s"}),
    "job_rejected": frozenset({"reason"}),
    # cross-job micro-batching (serve.batcher): one SHARED packed-bucket
    # dispatch coalescing several jobs' cluster work.  `jobs` lists the
    # member job ids; `n_clusters` is the merged size; `window_wait_s`
    # the collection wait; occupancy/fresh-compile/plan-cache deltas
    # attribute the one dispatch's device work that no single job's
    # run_end can claim.  status="shared" ran the coalesced dispatch;
    # "fallback_solo" means the shared pass failed and every member ran
    # solo (additive fields — pre-batching consumers keep validating).
    "batch_dispatch": frozenset(
        {"batch_id", "jobs", "n_jobs", "n_clusters", "window_wait_s",
         "status"}
    ),
    "serve_drain": frozenset({"n_rejected"}),
    # on-demand device profiling (`specpride profile` against a live
    # daemon): one bounded jax.profiler capture window
    "profile_start": frozenset({"seconds"}),
    "profile_done": frozenset({"seconds", "trace_dir"}),
    "bench_run": frozenset({"method", "phases_s"}),
    "run_end": frozenset({"counters", "phases_s", "elapsed_s", "device"}),
    # v2: one finished tracing span (observability.tracing).  The span's
    # end time is the envelope `mono`; start = mono - dur_s.  Optional
    # `labels` carries the per-span annotations (kernel, rows, ...).
    "span": frozenset({"name", "dur_s", "depth"}),
}


def _json_default(obj):
    """Journals must never crash a run over a numpy scalar in a payload."""
    for cast in (int, float):
        try:
            return cast(obj)
        except (TypeError, ValueError):
            continue
    return str(obj)


class Journal:
    """Append-only JSONL event writer.  Line-buffered so each event hits
    the filesystem as one complete line — tailable mid-run, and a crash
    loses at most the event being written."""

    enabled = True

    def __init__(self, path: str | os.PathLike):
        self.path = str(path)
        # one journal is shared by the CLI thread, the pipelined executor's
        # packer thread, and the fetch pool; a lock keeps each event line
        # whole (TextIOWrapper gives no cross-thread write atomicity)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", buffering=1, encoding="utf-8")
        # a kill mid-write leaves a torn final line with no newline; a
        # resumed run appending straight onto it would corrupt BOTH its
        # own run_start and the torn event — heal the seam first
        try:
            with open(self.path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() > 0:
                    fh.seek(-1, os.SEEK_END)
                    if fh.read(1) != b"\n":
                        self._fh.write("\n")
        except OSError:
            pass

    def emit(self, event: str, **fields) -> dict:
        rec = {
            "v": SCHEMA_VERSION,
            "ts": time.time(),
            "mono": time.perf_counter(),
            "event": event,
        }
        rec.update(fields)
        line = json.dumps(rec, default=_json_default) + "\n"
        with self._lock:
            # a multi-thread producer (the serving daemon's reader
            # threads) may race close(); dropping a late event beats
            # crashing the thread on a closed file
            if not self._fh.closed:
                self._fh.write(line)
        return rec

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NullJournal:
    """No-op stand-in so call sites never branch on '--journal given?'."""

    enabled = False
    path = None

    def emit(self, event: str, **fields) -> dict:
        return {}

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullJournal":
        return self

    def __exit__(self, *exc) -> None:
        pass


def open_journal(path: str | None) -> Journal | NullJournal:
    return Journal(path) if path else NullJournal()


def validate_event(rec: object) -> list[str]:
    """Schema-violation messages for one decoded journal line (empty list
    when valid)."""
    problems: list[str] = []
    if not isinstance(rec, dict):
        return [f"event is not an object: {rec!r}"]
    if rec.get("v") not in ACCEPTED_VERSIONS:
        problems.append(f"unsupported schema version {rec.get('v')!r}")
    if not isinstance(rec.get("ts"), (int, float)):
        problems.append("missing/non-numeric 'ts'")
    if rec.get("v") == 2 and not isinstance(rec.get("mono"), (int, float)):
        problems.append("missing/non-numeric 'mono' (required in v2)")
    event = rec.get("event")
    required = EVENT_FIELDS.get(event)
    if required is None:
        problems.append(f"unknown event type {event!r}")
    else:
        missing = sorted(required - rec.keys())
        if missing:
            problems.append(f"{event}: missing fields {missing}")
    return problems


def read_events(path: str) -> tuple[list[dict], list[str]]:
    """Decode one journal file.  Returns ``(events, violations)``;
    violations carry ``path:line:`` prefixes so a multi-journal report
    stays attributable."""
    events: list[dict] = []
    violations: list[str] = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                violations.append(f"{path}:{lineno}: invalid JSON ({e.msg})")
                continue
            problems = validate_event(rec)
            for p in problems:
                violations.append(f"{path}:{lineno}: {p}")
            # only schema-valid events reach the summary: consumers may then
            # index required fields without re-checking (an invalid line is
            # still reported above and fails `specpride stats`)
            if not problems:
                events.append(rec)
    return events, violations


def expand_parts(path: str) -> tuple[list[str], list[str]]:
    """Resolve a journal path to its file list, rank-aware like
    ``merge-parts``: the path itself if it exists, else its
    ``<path>.part<id>`` shards ordered by parsed rank (NOT lexically).
    Returns ``(paths, warnings)``; a gap in the rank sequence is a
    warning, not an error — a post-mortem of a dead run must still read
    the ranks that DID write."""
    if os.path.exists(path):
        return [path], []
    parts = glob.glob(glob.escape(path) + ".part*")
    if not parts:
        return [], [f"no journal at {path} and no {path}.part* shards"]
    ranked, warnings = [], []
    for p in parts:
        suffix = p.rsplit(".part", 1)[1]
        if suffix.isdigit():
            ranked.append((int(suffix), p))
        else:
            warnings.append(f"unrecognized part name {p}")
    ranked.sort()
    ranks = [r for r, _ in ranked]
    missing = sorted(set(range(max(ranks) + 1)) - set(ranks)) if ranks else []
    if missing:
        warnings.append(
            f"{path}: rank gap — have {ranks}, missing {missing} "
            "(a rank died before writing its journal?)"
        )
    return [p for _, p in ranked], warnings
