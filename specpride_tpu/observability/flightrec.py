"""Flight recorder: always-on black-box capture + the incident plane.

The classic aviation pattern, applied to the journal stream: an
always-on, lock-cheap bounded ring of recent journal records per
process, plus the :mod:`~specpride_tpu.observability.detect` health
detectors folding the same stream — and, when a detector fires, an
ATOMIC incident bundle dumped under ``--incident-dir`` with everything
a post-mortem needs and nothing unbounded: the ring, live thread
stacks, a ``/metrics`` exposition snapshot, the autotune knob state,
the host's config digest, and the trailing journal window.

Wiring contract (mirrors the autotune controller exactly):

* ``off`` never constructs a recorder at all — the kill switch is the
  absence of this object, so an off run is byte-identical to a
  recorder-free build.
* ``observe`` journals every detector firing as an ``incident`` event
  (id, evidence, dedup accounting) without writing bundles — the safe
  rollout mode.
* ``on`` also dumps the bundle, atomically: everything is written into
  a ``.tmp-<pid>`` staging directory and renamed into place, so a kill
  mid-dump leaves only debris the read side ignores, never a torn
  bundle.

The recorder attaches via ``Journal.attach_tap`` — catch-up first, so
ring + detector state equal ``fold(file)`` from line one — and does NO
journal emit from inside the tap (the tap runs under the journal's
write lock; emitting there would deadlock).  Firings queue to a
dedicated recorder thread that dumps bundles and journals the
``incident`` events; detectors ignore ``incident`` events, so the
recorder never feeds back on itself.

``specpride incident-replay`` (:func:`replay_incidents`) refolds a
finished journal through the same :class:`~.detect.DetectorSet` and
requires every recorded firing — id, reason, clock, evidence, trace
id, dedup suppression count — to re-derive bit-exact from the stream
alone, the same determinism audit ``autotune-replay`` runs on the
controller.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import queue
import sys
import threading
import traceback

from specpride_tpu.observability.detect import DetectorSet
from specpride_tpu.observability.journal import read_events
from specpride_tpu.observability.stats import logger

# manifest schema for on-disk bundles (bumped on layout changes; the
# read side refuses manifests from the future)
BUNDLE_SCHEMA = 1

_TMP_MARKER = ".tmp-"


class RingBuffer:
    """Bounded ring of journal records.

    Appends happen under the journal write lock (the tap), snapshots
    from any thread: ``collections.deque`` with ``maxlen`` gives
    C-level, GIL-atomic append-with-overwrite, and :meth:`snapshot`
    retries the rare copy that catches a concurrent mutation — readers
    never block writers and never see a torn record."""

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1 ({capacity})")
        self.capacity = int(capacity)
        self._dq: collections.deque = collections.deque(maxlen=capacity)
        self.appended = 0

    def append(self, rec: dict) -> None:
        self._dq.append(rec)
        self.appended += 1

    def snapshot(self) -> list:
        """A point-in-time copy, oldest first."""
        while True:
            try:
                return list(self._dq)
            except RuntimeError:
                # the deque mutated mid-iteration (an append raced the
                # copy) — retry; the window is a few C instructions
                continue

    def __len__(self) -> int:
        return len(self._dq)


def _format_stacks() -> str:
    """Every live thread's Python stack via ``sys._current_frames`` —
    the 'what was everyone doing' page of the black box."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts: list[str] = []
    for tid, frame in sorted(sys._current_frames().items()):
        parts.append(f"--- thread {tid} ({names.get(tid, '?')}) ---\n")
        parts.append("".join(traceback.format_stack(frame)))
    return "".join(parts)


def _journal_tail(path: str | None, max_lines: int) -> list[str]:
    """The last ``max_lines`` complete lines of the live journal file
    (bounded read from the end — a days-long journal must not make a
    dump unbounded)."""
    if not path or max_lines <= 0:
        return []
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - 256 * 1024))
            chunk = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    lines = chunk.splitlines()
    if size > 256 * 1024 and lines:
        lines = lines[1:]  # drop the torn head of the window
    return lines[-max_lines:]


def config_digest(config: dict) -> str:
    """Stable digest of a host's boot-time config/flag view — lets an
    operator diff 'what exactly was this daemon running' across
    incidents without comparing whole dicts."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class FlightRecorder:
    """One process's black box: ring + detectors + bundle dumper.

    ``mode``: ``observe`` (journal firings, no bundles) or ``on``
    (also dump bundles under ``incident_dir``).  ``off`` never
    constructs a recorder — same kill-switch discipline as the
    autotune :class:`~specpride_tpu.autotune.controller.Controller`.

    Capture hooks (all optional, all best-effort — a failing hook
    degrades that bundle section, never the host):

    * ``metrics_fn()`` -> Prometheus exposition text
      (``metrics.prom``)
    * ``autotune_fn()`` -> the controller's knob/decision state
      (``autotune.json``)
    * ``config`` — the host's boot config dict (``config.json``, with
      its sha256 digest)
    * ``extra_fn()`` -> any further host state, e.g. the elastic
      coordinator's lease counters (``host.json``)
    """

    def __init__(
        self,
        journal,
        *,
        mode: str = "observe",
        incident_dir: str | None = None,
        ring: int = 512,
        journal_tail: int = 200,
        params: dict | None = None,
        metrics_fn=None,
        autotune_fn=None,
        config: dict | None = None,
        extra_fn=None,
        telemetry=None,
    ):
        if mode not in ("observe", "on"):
            raise ValueError(
                f"flightrec mode {mode!r} must be observe or on"
            )
        if mode == "on" and not incident_dir:
            raise ValueError(
                "flightrec mode 'on' dumps bundles and therefore "
                "requires an --incident-dir"
            )
        self.journal = journal
        self.mode = mode
        self.incident_dir = incident_dir
        self.ring = RingBuffer(ring)
        self.detect = DetectorSet(params)
        self.journal_tail = int(journal_tail)
        self.metrics_fn = metrics_fn
        self.autotune_fn = autotune_fn
        self.config = dict(config or {})
        self.extra_fn = extra_fn
        self.telemetry = telemetry  # ServeTelemetry (or None)
        self.bundles = 0
        self.bundle_errors = 0
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread: threading.Thread | None = None
        self._stopped = False

    # -- the journal tap ------------------------------------------------

    def observe(self, rec) -> None:
        """Fold one record (runs UNDER the journal write lock — no
        emit, no I/O here beyond the ring append; firings queue to the
        recorder thread)."""
        if isinstance(rec, dict):
            self.ring.append(rec)
        for firing in self.detect.observe(rec):
            self._q.put(firing)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "FlightRecorder":
        """Attach to the journal WITH catch-up (ring + detector state
        equal ``fold(file)`` from line one — the replay invariant) and
        start the recorder thread."""
        if self.incident_dir:
            os.makedirs(self.incident_dir, exist_ok=True)
        self.journal.attach_tap(self.observe)
        self._thread = threading.Thread(
            target=self._run, name="flightrec", daemon=True,
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while True:
            firing = self._q.get()
            if firing is None:
                return
            try:
                self._process(firing)
            except Exception:  # noqa: BLE001 - the recorder must never
                logger.exception(  # take the host down
                    "flightrec: processing incident failed"
                )

    def stop(self) -> None:
        """Detach the tap, drain every queued firing (each is still
        journaled — a drain must not swallow evidence), stop the
        thread.  Called BEFORE the host closes its journal, next to
        the autotune controller's stop."""
        if self._stopped:
            return
        self._stopped = True
        self.journal.detach_tap(self.observe)
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    # -- incident processing (recorder thread) --------------------------

    def _process(self, firing: dict) -> None:
        bundled = False
        bundle_fields: dict = {}
        if self.mode == "on":
            try:
                bundle_dir = self._write_bundle(firing)
            except Exception as e:  # noqa: BLE001 - degrade to observe
                self.bundle_errors += 1
                bundle_fields["bundle_error"] = (
                    f"{type(e).__name__}: {e}"
                )
                logger.warning(
                    "flightrec: bundle dump for %s failed: %s",
                    firing["incident_id"], e,
                )
            else:
                bundled = True
                self.bundles += 1
                bundle_fields["bundle_dir"] = bundle_dir
        self.journal.emit(
            "incident",
            detector=firing["detector"],
            incident_id=firing["incident_id"],
            reason=firing["reason"],
            clock=firing["clock"],
            evidence=firing["evidence"],
            suppressed=firing["suppressed"],
            trace_id=firing["trace_id"],
            mode=self.mode,
            bundled=bundled,
            **bundle_fields,
        )
        if self.telemetry is not None:
            try:
                self.telemetry.incident(
                    detector=firing["detector"],
                    suppressed=int(firing["suppressed"]),
                )
            except Exception:  # noqa: BLE001 - metrics best effort
                pass
        logger.warning(
            "incident %s: %s (%s%s)", firing["incident_id"],
            firing["reason"], self.mode,
            f", bundle {bundle_fields.get('bundle_dir')}"
            if bundled else "",
        )

    def _write_bundle(self, firing: dict) -> str:
        """Dump one atomic bundle; returns its final directory.  Stage
        into ``<final>.tmp-<pid>`` and rename: a SIGKILL mid-dump
        leaves only a ``.tmp-`` directory the read side skips."""
        name = f"{firing['incident_id']}-{firing['detector']}"
        final = os.path.join(self.incident_dir, name)
        if os.path.isdir(final):
            return final  # already dumped (a resumed catch-up refold)
        tmp = f"{final}{_TMP_MARKER}{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        files: list[str] = []

        def _put(fname: str, text: str) -> None:
            with open(os.path.join(tmp, fname), "w",
                      encoding="utf-8") as fh:
                fh.write(text)
            files.append(fname)

        ring = self.ring.snapshot()
        _put("ring.jsonl", "".join(
            json.dumps(r, default=str) + "\n" for r in ring
        ))
        _put("stacks.txt", _format_stacks())
        tail = _journal_tail(
            getattr(self.journal, "path", None), self.journal_tail
        )
        if tail:
            _put("journal_tail.jsonl", "\n".join(tail) + "\n")
        if self.metrics_fn is not None:
            try:
                _put("metrics.prom", self.metrics_fn())
            except Exception as e:  # noqa: BLE001 - section degrades
                _put("metrics.error.txt", f"{type(e).__name__}: {e}\n")
        if self.autotune_fn is not None:
            try:
                _put("autotune.json", json.dumps(
                    self.autotune_fn(), indent=2, sort_keys=True,
                    default=str,
                ) + "\n")
            except Exception as e:  # noqa: BLE001 - section degrades
                _put("autotune.error.txt", f"{type(e).__name__}: {e}\n")
        if self.extra_fn is not None:
            try:
                _put("host.json", json.dumps(
                    self.extra_fn(), indent=2, sort_keys=True,
                    default=str,
                ) + "\n")
            except Exception as e:  # noqa: BLE001 - section degrades
                _put("host.error.txt", f"{type(e).__name__}: {e}\n")
        _put("config.json", json.dumps(
            {"config": self.config,
             "digest": config_digest(self.config)},
            indent=2, sort_keys=True, default=str,
        ) + "\n")
        # the manifest is written LAST inside the staging dir, then the
        # whole dir renames into place — a bundle either exists with
        # its complete manifest or not at all
        manifest = {
            "schema": BUNDLE_SCHEMA,
            "incident": {**firing, "mode": self.mode},
            "ring_records": len(ring),
            "files": sorted(files),
            "journal": getattr(self.journal, "path", None),
            "pid": os.getpid(),
        }
        with open(os.path.join(tmp, "manifest.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True,
                      default=str)
            fh.write("\n")
        os.rename(tmp, final)
        return final

    # -- live status ----------------------------------------------------

    def status(self) -> dict:
        return {
            "mode": self.mode,
            **self.detect.status(),
            "bundles": self.bundles,
            "bundle_errors": self.bundle_errors,
            "ring": len(self.ring),
            "ring_capacity": self.ring.capacity,
            **({"incident_dir": self.incident_dir}
               if self.incident_dir else {}),
        }


# -- read side: bundles on disk ------------------------------------------


def list_bundles(incident_dir: str) -> tuple[list[dict], list[str]]:
    """Scan an incident directory for complete bundles.  Returns
    ``(bundles, warnings)``: each bundle is its manifest plus a
    ``"dir"`` key; ``.tmp-`` staging debris (a kill mid-dump) is
    skipped silently — that is exactly the atomicity contract —
    while a directory MISSING its manifest is a warning."""
    bundles: list[dict] = []
    warnings: list[str] = []
    try:
        entries = sorted(os.listdir(incident_dir))
    except OSError as e:
        return [], [f"cannot read {incident_dir}: {e}"]
    for entry in entries:
        path = os.path.join(incident_dir, entry)
        if not os.path.isdir(path) or _TMP_MARKER in entry:
            continue
        mpath = os.path.join(path, "manifest.json")
        try:
            with open(mpath, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            warnings.append(f"{path}: unreadable manifest ({e})")
            continue
        if manifest.get("schema", 0) > BUNDLE_SCHEMA:
            warnings.append(
                f"{path}: bundle schema {manifest.get('schema')} is "
                f"newer than this build ({BUNDLE_SCHEMA})"
            )
            continue
        manifest["dir"] = path
        bundles.append(manifest)
    bundles.sort(
        key=lambda m: float(m.get("incident", {}).get("clock") or 0.0)
    )
    return bundles, warnings


def find_bundle(incident_dir: str, incident_id: str) -> dict | None:
    """The one bundle whose incident id matches (prefix match accepted,
    like git — ids are content-derived hex)."""
    bundles, _ = list_bundles(incident_dir)
    hits = [
        b for b in bundles
        if str(b.get("incident", {}).get("incident_id", ""))
        .startswith(incident_id)
    ]
    return hits[0] if len(hits) == 1 else None


# -- offline replay audit ------------------------------------------------


def replay_incidents(path: str) -> dict:
    """Re-derive every ``incident`` event under ``path`` from the
    journal stream alone and diff against what the recorder journaled.

    Per-process streams replay independently (rotated segments chain,
    ``.part<rank>`` shards split) — the same grouping as
    ``autotune-replay``.  Within one stream the recorder journals
    firings in trigger order, so the k-th recorded incident must match
    the k-th refolded firing on every stream-derivable field: detector,
    incident id, reason, trigger clock, evidence payload, trace id and
    the dedup ``suppressed`` count.  ``bundled`` must be consistent
    with the recorded ``mode``.  Firings the refold derives that never
    reached the file (a process killed before its recorder drained)
    are warnings, not failures — the stream holds MORE evidence than
    the dead recorder could write, never less."""
    from specpride_tpu.autotune.replay import _same, _streams

    streams, warnings = _streams(path)
    result: dict = {
        "incidents": 0, "reproduced": 0, "bundled": 0,
        "suppressed": 0, "by_detector": {},
        "mismatches": [], "unjournaled": [],
        "violations": [], "warnings": list(warnings),
        "streams": len(streams),
    }
    compare = ("detector", "incident_id", "reason", "clock",
               "evidence", "trace_id", "suppressed")
    for key in sorted(streams):
        detect = DetectorSet()
        derived: collections.deque = collections.deque()
        for p in streams[key]:
            events, violations = read_events(p)
            result["violations"].extend(violations)
            for rec in events:
                # fold first: DetectorSet ignores incident events, so
                # feeding every record keeps one code path with live
                for firing in detect.observe(rec):
                    derived.append(firing)
                if rec.get("event") != "incident":
                    continue
                result["incidents"] += 1
                det = rec.get("detector")
                result["by_detector"][det] = (
                    result["by_detector"].get(det, 0) + 1
                )
                if rec.get("bundled"):
                    result["bundled"] += 1
                result["suppressed"] += int(rec.get("suppressed") or 0)
                where = (
                    f"{p}: {det} @ {rec.get('clock')} "
                    f"({rec.get('incident_id')})"
                )
                if not derived:
                    result["mismatches"].append(
                        f"{where}: recorded incident has NO refolded "
                        "firing (detector changed since the journal "
                        "was written?)"
                    )
                    continue
                firing = derived.popleft()
                got = {k: firing.get(k) for k in compare}
                want = {
                    k: (int(rec.get(k) or 0) if k == "suppressed"
                        else rec.get(k))
                    for k in compare
                }
                ok = True
                if not _same(got, want):
                    for k in compare:
                        if not _same(got[k], want[k]):
                            result["mismatches"].append(
                                f"{where}: {k} refolded {got[k]!r} "
                                f"!= recorded {want[k]!r}"
                            )
                    ok = False
                mode = rec.get("mode")
                if mode not in ("observe", "on"):
                    result["mismatches"].append(
                        f"{where}: unknown mode {mode!r}"
                    )
                    ok = False
                elif mode == "observe" and rec.get("bundled"):
                    result["mismatches"].append(
                        f"{where}: bundled=true in observe mode"
                    )
                    ok = False
                elif (mode == "on" and not rec.get("bundled")
                        and "bundle_error" not in rec):
                    result["mismatches"].append(
                        f"{where}: mode on but bundled=false with no "
                        "bundle_error"
                    )
                    ok = False
                if ok:
                    result["reproduced"] += 1
        for firing in derived:
            result["unjournaled"].append(
                f"{key}: {firing['detector']} @ {firing['clock']} "
                f"({firing['incident_id']}) refolds but was never "
                "journaled (recorder died before draining?)"
            )
    result["ok"] = (
        not result["mismatches"] and not result["violations"]
    )
    return result


def render_incident_replay(result: dict, out) -> None:
    """Human summary for ``specpride incident-replay``."""
    out.write(
        f"incident-replay: {result['incidents']} incident(s) across "
        f"{result['streams']} stream(s), {result['bundled']} bundled, "
        f"{result['suppressed']} suppressed by dedup\n"
    )
    out.write(
        f"  reproduced: {result['reproduced']}/{result['incidents']}\n"
    )
    for det in sorted(result["by_detector"]):
        out.write(f"  {det}: {result['by_detector'][det]}\n")
    for kind in ("mismatches", "unjournaled", "violations", "warnings"):
        for line in result[kind]:
            out.write(f"  {kind.rstrip('es') if kind.endswith('es') else kind}: {line}\n")
    out.write("ok\n" if result["ok"] else "FAILED\n")
