"""specpride_tpu: a TPU-native framework for merging clustered MS/MS spectra.

Re-designed from scratch with the capabilities of the specpride reference
(EuBIC 2020 "methods to merge spectra" hackathon): given MS/MS spectra grouped
into clusters, produce one representative spectrum per cluster by

* consensus by m/z-grid binning        (ref: src/binning.py:170-231)
* consensus by gap-clustering average  (ref: src/average_spectrum_clustering.py:26-103)
* best-PSM-score member selection      (ref: src/best_spectrum.py:67-100)
* medoid under binned-dot-product      (ref: src/most_similar_representative.py:60-111)

plus clustered-MGF format conversion, quality metrics (binned cosine,
b/y-ion fraction) and mirror plotting.

Architecture (TPU-first, not a port):

* ``specpride_tpu.data``     ragged peak model + bucketed padded device batches
* ``specpride_tpu.io``       host-side MGF / mzML / TSV ingest (C++ fast path)
* ``specpride_tpu.ops``      JAX/XLA + Pallas device kernels (the compute core)
* ``specpride_tpu.backends`` numpy oracle and tpu execution backends (the
  four merge strategies as a uniform ``run_*`` API on each)
* ``specpride_tpu.parallel`` device mesh / sharding / multi-host scale-out
* ``specpride_tpu.metrics``  quality metrics on device
"""

__version__ = "0.4.0"

from specpride_tpu.config import (
    BinMeanConfig,
    GapAverageConfig,
    MedoidConfig,
    BestSpectrumConfig,
    CosineConfig,
)
from specpride_tpu.data.peaks import Spectrum, Cluster

__all__ = [
    "BinMeanConfig",
    "GapAverageConfig",
    "MedoidConfig",
    "BestSpectrumConfig",
    "CosineConfig",
    "Spectrum",
    "Cluster",
    "__version__",
]
